#!/usr/bin/env python
"""Batch grader: run the lab test suites for each submission and scrape
one summary line per (submission, lab).

Mirrors the reference's grading/grader.py:44-58 workflow — each
submission is graded in a scratch overlay (the framework tree with the
submission's ``dslabs_tpu/labs/`` dropped in), each lab runs
``TIMES_TO_RUN`` times under a timeout, and the per-test JSON results
written by run_tests.py are aggregated into a CSV.

Usage:
    python grading/grader.py --submissions subs/ --labs 1 2 3 --out grades.csv

``subs/`` holds one directory per student, each containing a
``dslabs_tpu/labs/`` tree (or a ``labs/`` tree at its root).  With no
--submissions, the framework's own reference labs are graded (a
self-check that every lab scores full points).
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIMES_TO_RUN = 2          # best score of N runs (grading/grader.py:44)
TIMEOUT_SECS = 600        # per lab run (reference: 10-minute timeout)


def _overlay(submission: str | None, extra_ignores: tuple = ()) -> str:
    """Copy the framework into a scratch dir, dropping in the
    submission's labs tree when given.  ``extra_ignores`` keeps the
    submissions directory itself out of the overlays when it lives under
    the repo root (otherwise N overlays each copy all N submissions)."""
    scratch = tempfile.mkdtemp(prefix="dslabs-grade-")
    dst = os.path.join(scratch, "repo")
    shutil.copytree(REPO, dst, ignore=shutil.ignore_patterns(
        ".git", "__pycache__", ".pytest_cache", "traces", "grading",
        *extra_ignores))
    if submission:
        for rel in ("dslabs_tpu/labs", "labs"):
            src = os.path.join(submission, rel)
            if os.path.isdir(src):
                target = os.path.join(dst, "dslabs_tpu", "labs")
                shutil.rmtree(target)
                shutil.copytree(src, target)
                break
        else:
            raise FileNotFoundError(
                f"{submission}: no dslabs_tpu/labs/ or labs/ tree")
    return dst


def _run_lab(tree: str, lab: str, results_path: str) -> dict:
    """One scored lab run; returns the parsed JSON results (or a stub)."""
    # Belt and braces: the CLI flag below is authoritative; the env var
    # (read by dslabs_tpu/utils/flags.py) covers run_tests.py variants in
    # submissions that predate the flag.
    env = dict(os.environ, DSLABS_RESULTS_OUTPUT_FILE=results_path)
    try:
        proc = subprocess.run(
            [sys.executable, "run_tests.py", "--lab", lab,
             "--results-file", results_path],
            cwd=tree, env=env, capture_output=True, text=True,
            timeout=TIMEOUT_SECS)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {TIMEOUT_SECS}s",
                "points": 0, "total": 0, "passed": 0, "tests": 0}
    if os.path.exists(results_path):
        with open(results_path) as f:
            data = json.load(f)
        return {
            "points": data.get("points_earned", 0),
            "total": data.get("points_available", 0),
            "passed": data.get("num_passed", 0),
            "tests": data.get("num_tests", 0),
            "rc": rc,
        }
    tail = (proc.stderr or proc.stdout).strip().splitlines()
    return {"error": tail[-1][:200] if tail else f"rc={rc}",
            "points": 0, "total": 0, "passed": 0, "tests": 0}


def grade(submission: str | None, labs: list, name: str,
          extra_ignores: tuple = ()) -> list:
    tree = _overlay(submission, extra_ignores)
    rows = []
    try:
        for lab in labs:
            best = None
            for attempt in range(TIMES_TO_RUN):
                res = _run_lab(tree, lab, os.path.join(
                    tree, f"results-lab{lab}-{attempt}.json"))
                if best is None or res["points"] > best["points"]:
                    best = res
                if best.get("total") and best["points"] == best["total"]:
                    break     # full marks; no need to re-run
            rows.append({"submission": name, "lab": lab, **best})
            print(f"{name} lab {lab}: {best.get('points', 0)}/"
                  f"{best.get('total', '?')} points "
                  f"({best.get('passed', 0)}/{best.get('tests', '?')} tests)"
                  + (f"  [{best['error']}]" if "error" in best else ""),
                  flush=True)
    finally:
        shutil.rmtree(os.path.dirname(tree), ignore_errors=True)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--submissions", help="directory of per-student trees "
                    "(default: grade the reference labs in place)")
    ap.add_argument("--labs", nargs="+", default=["0", "1", "2", "3", "4"])
    ap.add_argument("--out", default="grades.csv")
    args = ap.parse_args()

    all_rows = []
    if args.submissions:
        subs_abs = os.path.abspath(args.submissions)
        # Path-component check, not a string prefix: "/root/repo-subs"
        # must NOT match a repo at "/root/repo" (a sibling dir's basename
        # would silently vanish from every overlay copy).
        ignores = ((os.path.basename(subs_abs.rstrip(os.sep)),)
                   if os.path.commonpath([subs_abs, REPO]) == REPO else ())
        for name in sorted(os.listdir(args.submissions)):
            path = os.path.join(args.submissions, name)
            if os.path.isdir(path):
                all_rows += grade(path, args.labs, name, ignores)
    else:
        all_rows += grade(None, args.labs, "reference")

    fields = ["submission", "lab", "points", "total", "passed", "tests",
              "rc", "error"]
    with open(args.out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
        w.writeheader()
        w.writerows(all_rows)
    print(f"wrote {args.out} ({len(all_rows)} rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
