#!/usr/bin/env python
"""Distribute batch grading over ssh hosts and merge the results.

The TPU-native analog of the reference's grading/distributor.py:1-120
workflow: partition the submissions directory into one shard per host,
rsync the framework tree + shard + grader to each host's scratch
directory, run ``grading/grader.py`` there over ssh (one thread per
host), rsync each host's CSV back, and merge them into one output CSV.

Usage:
    python grading/distributor.py --submissions subs/ \
        --hosts hostA hostB --labs 1 2 3 --out grades.csv

or with a JSON config (mirroring the reference's config.json shape):
    python grading/distributor.py --config grading/config.json

config keys: ``submission_path``, ``hosts`` (list), ``labs`` (list),
``remote_dir`` (default /tmp/dslabs-grading), ``out``.

Hosts need passwordless ssh and a python3 with the framework's
dependencies on PATH.  A host that fails leaves its shard's rows out of
the merged CSV and is reported loudly (exit code 1), matching the
reference's missing-summary warning.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import shlex
import subprocess
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REMOTE_DIR = "/tmp/dslabs-grading"


def _sh(args, **kw) -> int:
    return subprocess.call(args, **kw)


def _partition(names, n):
    """Round-robin over sorted names: near-even shard sizes (the
    reference ceil-splits contiguously, grading/distributor.py; shard
    CONTENTS differ but the merge step is order-independent)."""
    shards = [[] for _ in range(n)]
    for i, name in enumerate(sorted(names)):
        shards[i % n].append(name)
    return shards


def _run_host(host: str, shard: list, subs_dir: str, labs: list,
              remote_dir: str, results_dir: str, errors: list) -> None:
    try:
        remote = f"{host}:{remote_dir}"
        if _sh(["ssh", host,
                f"rm -rf {shlex.quote(remote_dir)} && "
                f"mkdir -m 700 -p {shlex.quote(remote_dir)}/subs"]):
            raise RuntimeError("remote scratch setup failed")
        # Framework tree (sans VCS/cache noise), then this host's shard.
        if _sh(["rsync", "-a", "--exclude", ".git", "--exclude",
                "__pycache__", "--exclude", ".pytest_cache",
                f"{REPO}/", f"{remote}/repo"]):
            raise RuntimeError("framework rsync failed")
        for name in shard:
            if _sh(["rsync", "-a", os.path.join(subs_dir, name) + "/",
                    f"{remote}/subs/{name}"]):
                raise RuntimeError(f"submission rsync failed: {name}")
        lab_args = " ".join(shlex.quote(l) for l in labs)
        cmd = (f"cd {shlex.quote(remote_dir)}/repo && "
               f"python3 grading/grader.py --submissions ../subs "
               f"--labs {lab_args} --out ../grades.csv")
        if _sh(["ssh", host, cmd]):
            raise RuntimeError("remote grader failed")
        os.makedirs(results_dir, exist_ok=True)
        if _sh(["rsync", "-a", f"{remote}/grades.csv",
                os.path.join(results_dir, f"{host}-grades.csv")]):
            raise RuntimeError("results rsync failed")
    except Exception as e:  # collected, not raised: other hosts continue
        errors.append(f"{host}: {e}")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", help="JSON config (reference shape)")
    ap.add_argument("--submissions")
    ap.add_argument("--hosts", nargs="+")
    ap.add_argument("--labs", nargs="+", default=None)
    ap.add_argument("--remote-dir", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--results-dir", default="results")
    args = ap.parse_args(argv)

    if args.config:
        with open(args.config) as fd:
            cfg = json.load(fd)
        # CLI wins over config everywhere: every option defaults to None
        # so "explicitly passed" is unambiguous (a string test on argv
        # missed --labs=... and argparse prefix forms).
        args.submissions = args.submissions or os.path.expanduser(
            cfg.get("submission_path", ""))
        args.hosts = args.hosts or cfg.get("hosts", [])
        if args.labs is None:
            args.labs = cfg.get("labs")
        if args.remote_dir is None:
            args.remote_dir = cfg.get("remote_dir")
        if args.out is None:
            args.out = cfg.get("out")
    if args.labs is None:
        args.labs = ["0", "1", "2", "3", "4"]
    args.remote_dir = args.remote_dir or REMOTE_DIR
    args.out = args.out or "grades.csv"
    if not args.submissions or not args.hosts:
        ap.error("--submissions and --hosts required (or via --config)")

    # Clear stale per-host CSVs first: a failed host must be ABSENT from
    # the merge, not represented by a previous run's rows.
    for host in args.hosts:
        stale = os.path.join(args.results_dir, f"{host}-grades.csv")
        if os.path.exists(stale):
            os.remove(stale)

    names = [n for n in os.listdir(args.submissions)
             if os.path.isdir(os.path.join(args.submissions, n))]
    shards = _partition(names, len(args.hosts))
    errors: list = []
    threads = []
    for host, shard in zip(args.hosts, shards):
        if not shard:
            continue
        t = threading.Thread(
            target=_run_host,
            args=(host, shard, args.submissions, [str(l) for l in args.labs],
                  args.remote_dir, args.results_dir, errors))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()

    # ---- merge per-host CSVs (header once, rows concatenated)
    rows, header = [], None
    for host in args.hosts:
        path = os.path.join(args.results_dir, f"{host}-grades.csv")
        if not os.path.exists(path):
            continue
        with open(path) as fd:
            r = list(csv.reader(fd))
        if not r:
            continue
        header = header or r[0]
        rows.extend(r[1:])
    if header is not None:
        with open(args.out, "w", newline="") as fd:
            w = csv.writer(fd)
            w.writerow(header)
            w.writerows(rows)
        print(f"merged {len(rows)} rows from "
              f"{len([h for h in args.hosts])} hosts -> {args.out}")
    for e in errors:
        print(f"ERROR: {e}", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
