"""Benchmark: lab3 multi-Paxos BFS unique-states/minute on the TPU tensor
backend (BASELINE.md north star: >= 1e8 unique lab3-paxos states/min on a
v5e-8; this runs on whatever chips the driver provides).

The measured engine is the device-resident sharded BFS
(dslabs_tpu/tpu/sharded.py) over a mesh of all available devices — on one
chip the all_to_all degenerates to an identity and the loop still keeps
the frontier + visited set in HBM with one scalar sync per level.  All
device arithmetic is int32/uint32 (round 1 crashed the TPU worker inside
x64-emulated fingerprints; x64 is now banned from device code).

Always prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}:
configuration ladders down (chunk size, caps) on failure, and a final
fallback reports value 0.0 with the error string rather than crashing.
"""

import json
import sys
import time
import traceback

BASELINE_STATES_PER_MIN = 1e8


def _run_config(chunk_per_device: int, frontier_cap: int, visited_cap: int,
                max_secs: float):
    import jax

    from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    # Two clients widen the space enough to sustain large frontiers.
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=chunk_per_device,
        frontier_cap=frontier_cap, visited_cap=visited_cap, max_depth=1)
    search.run()  # warm-up: compiles the chunk/finish programs
    search.max_depth = 64
    search.max_secs = max_secs
    t0 = time.time()
    outcome = search.run()
    elapsed = max(time.time() - t0, 1e-9)
    return outcome.unique_states / elapsed * 60.0


def main() -> None:
    import jax

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    max_secs = 120.0 if on_tpu else 45.0
    ladder = [
        (2048, 1 << 17, 1 << 22),
        (512, 1 << 15, 1 << 20),
        (128, 1 << 13, 1 << 18),
    ]
    value, err = 0.0, None
    for chunk, f_cap, v_cap in ladder:
        try:
            value = _run_config(chunk, f_cap, v_cap, max_secs)
            err = None
            break
        except Exception:
            err = traceback.format_exc(limit=3)
            continue
    result = {
        "metric": ("lab3-paxos BFS unique states/min "
                   f"(sharded tensor backend, {platform}"
                   f" x{len(jax.devices())})"),
        "value": round(value, 1),
        "unit": "states/min",
        "vs_baseline": round(value / BASELINE_STATES_PER_MIN, 6),
    }
    if err is not None:
        result["error"] = err.strip().splitlines()[-1][:300]
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception:
        tb = traceback.format_exc(limit=3)
        print(json.dumps({
            "metric": "lab3-paxos BFS unique states/min (tensor backend)",
            "value": 0.0, "unit": "states/min", "vs_baseline": 0.0,
            "error": tb.strip().splitlines()[-1][:300],
        }))
        sys.exit(0)
