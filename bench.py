"""Benchmark: lab3 multi-Paxos BFS unique-states/minute on the TPU tensor
backend (BASELINE.md north star: >= 1e8 unique lab3-paxos states/min on a
v5e-8; this runs on whatever chips the driver provides).

The measured engine is the device-resident sharded BFS
(dslabs_tpu/tpu/sharded.py) over a mesh of all available devices — on one
chip the all_to_all degenerates to an identity and the loop still keeps
the frontier + visited set in HBM with one scalar sync per level.  All
device arithmetic is int32/uint32 (round 1 crashed the TPU worker inside
x64-emulated fingerprints; x64 is banned from device code).

Each ladder rung runs in a SUBPROCESS: a TPU worker crash on an oversized
config kills only that rung's process — the parent falls through to the
next rung instead of inheriting a dead TPU client (the round-1 failure
mode where rung 1's crash poisoned every retry).  Rungs run strict=False:
routing/frontier capacity drops truncate expansion beam-style and are
reported, while semantic overflow (net/timer caps, visited shard) still
aborts the rung.

Always prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time
import traceback

BASELINE_STATES_PER_MIN = 1e8

# (chunk_per_device, frontier_cap, visited_cap) — per device.  Round-3
# measured config: occupancy-compacted split event grids (EV_BUDGET
# below), packed P1B payloads, row-native expand, tail-compacted visited
# probe -> 4.00M unique states/min on one v5e chip at the lead rung
# (compile ~2-3 min cold, cached thereafter).
LADDER = [
    (8192, 1 << 19, 1 << 24),  # lead: ~495 ms/chunk steady; visited 16M
                               # keys/device (256 MB) reaches ~51% full
                               # at the end of the 120 s budget
    (1024, 1 << 18, 1 << 23),  # fallback if the big rung OOMs
    (64, 1 << 12, 1 << 18),
]
UPGRADE_LADDER = [
]
RUNG_TIMEOUT_SECS = 540.0
UPGRADE_TIMEOUT_SECS = 780.0
# Message/timer pair-slot budgets (ev_budget): covers the measured max
# valid events through depth ~17 (msgs p99 ~40 of net_cap 64, timers
# max 8 of 30); overflow truncates coverage beam-style and is counted
# in `dropped` like any frontier-cap drop.
EV_BUDGET = (40, 8)
# Strict budget: slightly wider message window; events past it WINDOW-
# SPILL (the chunk re-steps at the next window) instead of dropping, so
# this is a throughput knob, not a correctness bound.
EV_BUDGET_STRICT = (48, 8)


def _bench_protocol():
    import dataclasses

    from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol

    # Two clients widen the space enough to sustain large frontiers.
    # Goals are stripped: the bench measures sustained exploration
    # throughput, and a lucky beam hitting CLIENTS_DONE mid-run would end
    # it early with a run-dependent rate.
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    return dataclasses.replace(protocol, goals={})


def _run_rung(chunk_per_device: int, frontier_cap: int, visited_cap: int,
              max_secs: float) -> dict:
    import jax

    # Persistent compile cache: the expand program takes minutes to build;
    # repeat bench invocations on the same machine skip straight to run.
    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    mesh = make_mesh(len(jax.devices()))
    # NO checkpointing inside the measured window: dumping the multi-GB
    # carry through the device tunnel costs minutes (measured: a
    # checkpoint_every=4 rung spent 300 s saving and recorded 140
    # states/min), which is the whole budget.  Kill-resume is exercised
    # by tests/test_tpu_sharded.py and available to long strict
    # searches; a crashed rung here restarts fresh on the retry.
    # Warm-up depth 2, not 1: the final depth-limited level skips the
    # frontier promotion (count-only), so a depth-1 run would leave
    # _finish_level uncompiled and charge its compile to the window.
    search = ShardedTensorSearch(
        _bench_protocol(), mesh, chunk_per_device=chunk_per_device,
        frontier_cap=frontier_cap, visited_cap=visited_cap, max_depth=2,
        strict=False, ev_budget=EV_BUDGET)
    search.run()  # warm-up: compiles the chunk/finish programs
    search.max_depth = 64
    search.max_secs = max_secs
    outcome = search.run()
    elapsed = max(outcome.elapsed_secs, 1e-9)
    return {
        "value": outcome.unique_states / elapsed * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        "elapsed": elapsed,
    }


def _run_strict() -> dict:
    """The drop-free headline number: a strict (exact, nothing
    truncated) BFS of the bench protocol to depth 10 — every valid event
    of every reachable state expanded, dropped=0 enforced fatally by the
    engine (Search.java:405-505 semantics: BFS never silently narrows).

    Round-4 config: chunk 8192 (the beam rung's chunk — on one device
    the routing bucket holds the whole batch, so strict skips the
    in-chunk prefilter too), ev_budget (48, 8) with WINDOW SPILL (a
    state with more valid events re-steps its chunk at the next window —
    a perf knob, never a coverage cut), and the final level counts
    fresh states without building the ~4x-over-cap depth-10 frontier
    (count-only last level; the reference BFS likewise never queues
    states at the cutoff depth).  A warm-up run keeps compile time out
    of the measured window."""
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        _bench_protocol(), mesh, chunk_per_device=8192,
        frontier_cap=(1 << 20) + (1 << 18), visited_cap=1 << 24,
        max_depth=2, strict=True, ev_budget=EV_BUDGET_STRICT)
    search.run()  # warm-up: compiles chunk/finish/stats programs
    search.max_depth = 10
    t0 = time.time()
    outcome = search.run()
    return {
        "value": outcome.unique_states / max(outcome.elapsed_secs, 1e-9)
        * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        "elapsed": time.time() - t0,
    }


def _probe_platform() -> tuple:
    """Platform + device count WITHOUT initialising jax in this process —
    the accelerator must stay free for the rung subprocesses."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax, json; d = jax.devices(); "
             "print(json.dumps([d[0].platform, len(d)]))"],
            capture_output=True, text=True, timeout=180.0)
        return tuple(json.loads(out.stdout.strip().splitlines()[-1]))
    except Exception:
        return ("unknown", 0)


def _try_rung(chunk, f_cap, v_cap, max_secs, timeout=RUNG_TIMEOUT_SECS):
    """Run one ladder rung in a subprocess; (result dict, None) on
    success, (None, error string) otherwise."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--rung",
             str(chunk), str(f_cap), str(v_cap), str(max_secs)],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1]), None
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        return None, (tail[-1][:300] if tail
                      else f"rung chunk={chunk} exited rc={proc.returncode} "
                           "with no output")
    except subprocess.TimeoutExpired:
        return None, f"rung chunk={chunk} timed out after {timeout}s"
    except Exception:
        return None, traceback.format_exc(
            limit=2).strip().splitlines()[-1][:300]


def _try_strict(timeout=UPGRADE_TIMEOUT_SECS):
    """Best-effort strict probe in its own subprocess (a crash or
    timeout must never cost the headline number)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--strict"],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception:
        pass
    return None


def main() -> None:
    platform, n_dev = _probe_platform()
    max_secs = 120.0 if platform != "cpu" else 45.0
    best, err = None, None
    # The lead rung gets TWO attempts (a crash falls through to a fresh
    # retry before degrading).  CPU runs are a smoke test — only the
    # smallest rung is viable there.
    attempts = ([LADDER[0]] + LADDER if platform != "cpu"
                else [LADDER[-1]])
    for chunk, f_cap, v_cap in attempts:
        best, err = _try_rung(chunk, f_cap, v_cap, max_secs)
        if best is not None:
            break
    if best is not None and platform != "cpu":
        # A safe number is in hand — attempt the bigger-chunk upgrade and
        # keep whichever measured higher.
        for chunk, f_cap, v_cap in UPGRADE_LADDER:
            up, _ = _try_rung(chunk, f_cap, v_cap, max_secs,
                              timeout=UPGRADE_TIMEOUT_SECS)
            if up is not None and up["value"] > best["value"]:
                best = up
    value = best["value"] if best else 0.0
    result = {
        "metric": ("lab3-paxos BFS unique states/min "
                   f"(sharded tensor backend, {platform} x{n_dev})"),
        "value": round(value, 1),
        "unit": "states/min",
        "vs_baseline": round(value / BASELINE_STATES_PER_MIN, 6),
    }
    if best:
        result["detail"] = {k: best[k] for k in
                            ("unique", "explored", "depth", "end",
                             "dropped", "elapsed", "resumed")
                            if k in best}
    if err is not None and not best:
        result["error"] = err
    if best is not None and platform != "cpu":
        # The drop-free fidelity probe: an exact BFS (dropped=0) at
        # scale, reported alongside the beam rate (round-2 verdict: "the
        # north-star metric says unique states/min OF A REAL SEARCH").
        strict = _try_strict()
        if strict is not None:
            result["strict"] = strict
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--rung":
        chunk, f_cap, v_cap = map(int, sys.argv[2:5])
        print(json.dumps(_run_rung(chunk, f_cap, v_cap,
                                   float(sys.argv[5]))))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--strict":
        print(json.dumps(_run_strict()))
        sys.exit(0)
    try:
        main()
    except Exception:
        tb = traceback.format_exc(limit=3)
        print(json.dumps({
            "metric": "lab3-paxos BFS unique states/min (tensor backend)",
            "value": 0.0, "unit": "states/min", "vs_baseline": 0.0,
            "error": tb.strip().splitlines()[-1][:300],
        }))
        sys.exit(0)
