"""Benchmark: lab3 multi-Paxos BFS unique-states/minute on the TPU tensor
backend (BASELINE.md north star: >= 1e8 unique lab3-paxos states/min on a
v5e-8; this runs on whatever chips the driver provides).

The measured engine is the device-resident sharded BFS
(dslabs_tpu/tpu/sharded.py) over a mesh of all available devices — on one
chip the all_to_all degenerates to an identity and the loop still keeps
the frontier + visited set in HBM with one scalar sync per level.  All
device arithmetic is int32/uint32 (round 1 crashed the TPU worker inside
x64-emulated fingerprints; x64 is banned from device code).

Round-4 structure (the round-3 verdict's ordering):

1. **Calibration** — a shallow full-grid strict prefix measures the
   per-kind valid-event occupancy (max deliverable messages/timers per
   state) and derives the ev_budget with headroom: no hand-tuned budget
   constants.  Any state past the budget WINDOW-SPILLS (strict) — the
   budget is a throughput knob, never a correctness bound.
2. **The headline is the STRICT rate** — a drop-free exact BFS
   (dropped=0 enforced fatally; Search.java:405-505 semantics: BFS never
   silently narrows) to depth 10, count-only final level.
3. The beam rate (strict=False: routing/frontier-cap drops truncate
   coverage beam-style and are REPORTED) is secondary, in ``beam``.

Each phase runs in a SUBPROCESS: a TPU worker crash on an oversized
config kills only that phase's process — the parent falls through
instead of inheriting a dead TPU client (the round-1 failure mode).

Always prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time
import traceback

BASELINE_STATES_PER_MIN = 1e8

# (chunk_per_device, frontier_cap, visited_cap) — per device.  Beam
# ladder: round-3 measured config (occupancy-compacted split event
# grids, packed P1B payloads, row-native expand, tail-compacted visited
# probe -> 4.0M unique states/min on one v5e chip at the lead rung).
LADDER = [
    (8192, 1 << 19, 1 << 24),  # lead: ~495 ms/chunk steady at (40, 8)
    (1024, 1 << 18, 1 << 23),  # fallback if the big rung OOMs
    (64, 1 << 12, 1 << 18),
]
RUNG_TIMEOUT_SECS = 540.0
STRICT_TIMEOUT_SECS = 780.0
CALIBRATE_TIMEOUT_SECS = 420.0
# Fallback budgets if the calibration subprocess dies (its own crash
# must not zero the whole bench); values = the round-3 measured ones.
FALLBACK_EV_BUDGET = (40, 8)


def _bench_protocol():
    import dataclasses

    from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol

    # Two clients widen the space enough to sustain large frontiers.
    # Goals are stripped: the bench measures sustained exploration
    # throughput, and a lucky beam hitting CLIENTS_DONE mid-run would end
    # it early with a run-dependent rate.
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    return dataclasses.replace(protocol, goals={})


def _persistent_cache():
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jaxcache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _calibrate(max_depth: int = 7) -> dict:
    """Measure per-state valid-event occupancy on a shallow full-grid
    strict prefix; budgets = measured max + headroom (growth continues
    past the calibration depth — the spill covers the tail for strict,
    and beam counts the drops as before)."""
    import jax
    import jax.numpy as jnp

    _persistent_cache()

    from dslabs_tpu.tpu.engine import SENTINEL, timer_deliverable_mask
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    protocol = _bench_protocol()
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=1024, frontier_cap=1 << 17,
        visited_cap=1 << 22, max_depth=1, strict=True)

    def stats(carry):
        cur, cur_n = carry["cur"], carry["cur_n"][0]
        states = search.unflatten_rows(cur)
        valid = jnp.arange(cur.shape[0]) < cur_n
        msgs = jnp.sum(states["net"][:, :, 0] != SENTINEL, axis=1)
        tmrs = jnp.sum(jax.vmap(jax.vmap(timer_deliverable_mask))(
            states["timers"]), axis=(1, 2))
        return (jnp.max(jnp.where(valid, msgs, 0)),
                jnp.max(jnp.where(valid, tmrs, 0)))

    jstats = jax.jit(stats)
    bm = bt = 1
    with mesh:
        carry = search._init_carry(search.initial_state())
        max_n, depth, t0 = 1, 0, time.time()
        while max_n > 0 and depth < max_depth:
            depth += 1
            n_chunks = -(-(max_n + search.n_devices - 1) // search.cpd)
            for _ in range(n_chunks):
                carry = search._chunk_step(carry)
            _, _, _, _, max_n, _ = search._sync_checks(carry, depth, t0)
            carry = search._finish_level(carry)
            m, t = (int(x) for x in jax.tree.map(jnp.asarray,
                                                 jstats(carry)))
            bm, bt = max(bm, m), max(bt, t)
    p = search.p
    # Headroom: message occupancy keeps growing past the calibration
    # depth (~1/level); timers are structurally bounded by the retry
    # re-arm pattern.  Budgets clamp to the full grid.
    return {"bm": min(bm + bm // 2 + 4, p.net_cap),
            "bt": min(bt + 2, p.n_nodes * p.timer_cap),
            "measured": [bm, bt], "depth": depth}


def _run_rung(chunk_per_device: int, frontier_cap: int, visited_cap: int,
              max_secs: float, ev_budget) -> dict:
    import jax

    _persistent_cache()

    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    mesh = make_mesh(len(jax.devices()))
    # NO checkpointing inside the measured window by default (the async
    # incremental dump is cheap, but the headline stays unencumbered;
    # test_tpu_sharded.py covers kill-resume and the strict probe can
    # demonstrate checkpoint overhead via DSLABS_BENCH_CKPT=1).
    # Warm-up depth 2, not 1: the final depth-limited level skips the
    # frontier promotion (count-only), so a depth-1 run would leave
    # _finish_level uncompiled and charge its compile to the window.
    search = ShardedTensorSearch(
        _bench_protocol(), mesh, chunk_per_device=chunk_per_device,
        frontier_cap=frontier_cap, visited_cap=visited_cap, max_depth=2,
        strict=False, ev_budget=ev_budget)
    search.run()  # warm-up: compiles the chunk/finish programs
    search.max_depth = 64
    search.max_secs = max_secs
    outcome = search.run()
    elapsed = max(outcome.elapsed_secs, 1e-9)
    return {
        "value": outcome.unique_states / elapsed * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        "elapsed": elapsed,
    }


def _run_strict(ev_budget) -> dict:
    """The drop-free HEADLINE number: a strict (exact, nothing
    truncated) BFS of the bench protocol to depth 10 — every valid event
    of every reachable state expanded, dropped=0 enforced fatally.

    Config notes: chunk 8192 (on one device the routing bucket holds the
    whole batch, so strict skips the in-chunk prefilter too); the
    calibrated ev_budget WINDOW-SPILLS (a state with more valid events
    re-steps its chunk at the next window — never a coverage cut); the
    final level counts fresh states without building the ~4x-over-cap
    depth-10 frontier.  A warm-up run keeps compile out of the window.
    DSLABS_BENCH_CKPT=1 additionally runs async incremental checkpoints
    every 2 levels (the overhead-demonstration mode)."""
    import jax

    _persistent_cache()

    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    mesh = make_mesh(len(jax.devices()))
    ckpt = {}
    if os.environ.get("DSLABS_BENCH_CKPT"):
        ckpt = {"checkpoint_path": "/tmp/bench_strict.ckpt",
                "checkpoint_every": 2}
    search = ShardedTensorSearch(
        _bench_protocol(), mesh, chunk_per_device=8192,
        frontier_cap=(1 << 20) + (1 << 18), visited_cap=1 << 24,
        max_depth=2, strict=True, ev_budget=ev_budget, **ckpt)
    search.run()  # warm-up: compiles chunk/finish/stats programs
    search.max_depth = 10
    t0 = time.time()
    outcome = search.run()
    return {
        "value": outcome.unique_states / max(outcome.elapsed_secs, 1e-9)
        * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        "elapsed": time.time() - t0,
    }


def _probe_platform() -> tuple:
    """Platform + device count WITHOUT initialising jax in this process —
    the accelerator must stay free for the phase subprocesses."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax, json; d = jax.devices(); "
             "print(json.dumps([d[0].platform, len(d)]))"],
            capture_output=True, text=True, timeout=180.0)
        return tuple(json.loads(out.stdout.strip().splitlines()[-1]))
    except Exception:
        return ("unknown", 0)


def _sub(args, timeout):
    """Run a bench phase in a subprocess; (parsed dict, None) on success,
    (None, error string) otherwise."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1]), None
        tail = (proc.stderr or proc.stdout).strip().splitlines()
        return None, (tail[-1][:300] if tail
                      else f"{args[0]} exited rc={proc.returncode}")
    except subprocess.TimeoutExpired:
        return None, f"{args[0]} timed out after {timeout}s"
    except Exception:
        return None, traceback.format_exc(
            limit=2).strip().splitlines()[-1][:300]


def main() -> None:
    platform, n_dev = _probe_platform()
    max_secs = 120.0 if platform != "cpu" else 45.0
    on_cpu = platform == "cpu"

    # ---- phase 1: measured budgets (no hand-tuned constants)
    cal, cal_err = (None, "skipped on cpu") if on_cpu else _sub(
        ["--calibrate"], CALIBRATE_TIMEOUT_SECS)
    ev = ((cal["bm"], cal["bt"]) if cal else FALLBACK_EV_BUDGET)

    # ---- phase 2: the strict drop-free headline (two attempts)
    strict, strict_err = None, None
    if not on_cpu:
        for _ in range(2):
            strict, strict_err = _sub(
                ["--strict", str(ev[0]), str(ev[1])], STRICT_TIMEOUT_SECS)
            if strict is not None:
                break

    # ---- phase 3: the beam throughput rate (secondary)
    beam, beam_err = None, None
    attempts = ([LADDER[0]] + LADDER if not on_cpu else [LADDER[-1]])
    for chunk, f_cap, v_cap in attempts:
        beam, beam_err = _sub(
            ["--rung", str(chunk), str(f_cap), str(v_cap), str(max_secs),
             str(ev[0]), str(ev[1])], RUNG_TIMEOUT_SECS)
        if beam is not None:
            break

    lead = strict or beam
    value = lead["value"] if lead else 0.0
    kind = "strict BFS" if strict else "BFS (beam)"
    result = {
        "metric": (f"lab3-paxos {kind} unique states/min "
                   f"(sharded tensor backend, {platform} x{n_dev})"),
        "value": round(value, 1),
        "unit": "states/min",
        "vs_baseline": round(value / BASELINE_STATES_PER_MIN, 6),
        "ev_budget": list(ev),
    }
    if cal:
        result["calibration"] = cal
    if strict:
        result["strict"] = strict
    if beam:
        result["beam"] = beam
    errs = [e for e in (cal_err, strict_err, beam_err)
            if e and e != "skipped on cpu"]
    if errs and not lead:
        result["error"] = "; ".join(errs)
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--rung":
        chunk, f_cap, v_cap = map(int, sys.argv[2:5])
        ev = ((int(sys.argv[6]), int(sys.argv[7]))
              if len(sys.argv) > 7 else FALLBACK_EV_BUDGET)
        print(json.dumps(_run_rung(chunk, f_cap, v_cap,
                                   float(sys.argv[5]), ev)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--strict":
        ev = ((int(sys.argv[2]), int(sys.argv[3]))
              if len(sys.argv) > 3 else FALLBACK_EV_BUDGET)
        print(json.dumps(_run_strict(ev)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--calibrate":
        print(json.dumps(_calibrate()))
        sys.exit(0)
    try:
        main()
    except Exception:
        tb = traceback.format_exc(limit=3)
        print(json.dumps({
            "metric": "lab3-paxos strict BFS unique states/min "
                      "(tensor backend)",
            "value": 0.0, "unit": "states/min", "vs_baseline": 0.0,
            "error": tb.strip().splitlines()[-1][:300],
        }))
        sys.exit(0)
