"""Benchmark: lab3 multi-Paxos BFS unique-states/minute on the TPU tensor
backend (BASELINE.md north star: >= 1e8 unique lab3-paxos states/min on a
v5e-8; this runs on whatever chips the driver provides).

The measured engine is the device-resident sharded BFS
(dslabs_tpu/tpu/sharded.py) over a mesh of all available devices — on one
chip the all_to_all degenerates to an identity and the loop still keeps
the frontier + visited set in HBM with one scalar sync per level.  All
device arithmetic is int32/uint32 (round 1 crashed the TPU worker inside
x64-emulated fingerprints; x64 is banned from device code).

Round-6 structure — BOUNDED, DIAGNOSABLE, and NEVER SILENT (ISSUE 4:
BENCH_r04 was killed by the external timeout with NO JSON at all, and
BENCH_r05's preflight hung for 300 s so the CPU fallback never ran):

* A **hard global deadline** (DSLABS_BENCH_DEADLINE_SECS, default 480 s):
  every phase gets min(its own cap, time remaining); when the deadline
  expires the parent prints the best-so-far JSON line and exits 0 — a
  partial result with an attributable error beats a silent rc=124.
* **Guaranteed last-line JSON**: SIGTERM/SIGINT handlers plus a
  top-level try/except print the best-so-far result (tagged with the
  signal / traceback) before exiting 0 — an external ``timeout`` kill
  can no longer leave an empty tail.
* **Warden probes**: every phase child heartbeats on stderr and is
  watched by the shared silence monitor (tpu/warden.py LineWatch) — a
  WEDGED runtime stops heartbeating and is SIGKILLed at the silence
  budget (preflight: ~60 s), not at the full phase budget, so the
  240 s CPU fallback always fits inside the 480 s deadline.  The
  preflight kill is re-budgeted to <= 120 s total (the BENCH_r05 fix).
* A **pre-flight** subprocess (tiny matmul) distinguishes a wedged
  accelerator runtime from a slow compile: if 256x256 @ 256x256 cannot
  finish in its window, the bench reports "TPU runtime wedged" instead
  of hanging (the round-4 judging failure mode).
* **Heartbeats on stderr**: phase start/end lines here plus per-level
  lines from the search children (DSLABS_LEVEL_TIMING) — stderr passes
  straight through, stdout carries exactly one JSON line.
* **compile_secs** is measured (the warm-up run) and reported per phase.
* **Calibration is cached** (/tmp/dslabs_bench_cal.json, keyed by the
  protocol signature) so re-runs spend their window on the measurement.
* The **strict drop-free rate is the headline** (Search.java:405-505
  semantics: BFS never silently narrows; dropped=0 enforced fatally),
  one attempt, child-side time bound (a slow run returns a partial rate,
  TIME_EXHAUSTED, instead of a parent kill).  Beam runs only with time
  left and is reported under "beam" (dropped_states is a first-class
  field, warned past DSLABS_DROPPED_WARN); the **swarm explorer's**
  deep-probe rates (walkers/sec, unique-states/min, deepest depth —
  tpu/swarm.py) ride under "swarm", and the **capacity ladder's**
  1/8-visited-capacity spill rate vs uncapped (exact-parity flag,
  spill counters — tpu/spill.py) under "spill", all with the same
  always-reports guarantees.

Budget table (vs the 480 s deadline): docs/resilience.md.
"""

import json
import os
import signal
import subprocess
import sys
import time
import traceback

BASELINE_STATES_PER_MIN = 1e8

DEADLINE_SECS = float(os.environ.get("DSLABS_BENCH_DEADLINE_SECS", 480.0))
# Preflight: import + client init + one tiny (cached) compile.  Budget
# + slack is capped at 120 s TOTAL so a wedged preflight can never
# starve the 240 s CPU fallback out of the 480 s deadline (BENCH_r05
# hung here for 300 s and the round recorded value 0.0).
PREFLIGHT_CAP_SECS = 90.0
PREFLIGHT_KILL_SLACK_SECS = 30.0
# Heartbeat-silence kill budgets (tpu/warden.py LineWatch): the
# preflight child heartbeats between its boot stages, so a wedged
# runtime dies at ~60 s, not at the phase budget; measured phases
# heartbeat per level/phase and get a LONG leash — their one
# legitimate silence is a cold-cache XLA compile, which hit ~300 s on
# the tunnelled TPU runtime (BENCH_r05), and the preflight has already
# proven the runtime alive before any measured phase runs.
PREFLIGHT_SILENCE_SECS = float(os.environ.get(
    "DSLABS_BENCH_PREFLIGHT_SILENCE_SECS", 60.0))
PHASE_SILENCE_SECS = float(os.environ.get(
    "DSLABS_BENCH_SILENCE_SECS", 330.0))
CALIBRATE_CAP_SECS = 240.0
FALLBACK_CAP_SECS = 240.0    # wedged-TPU CPU-mesh fallback phase
STRICT_CAP_SECS = 420.0      # child budget cap; parent adds kill slack
BEAM_CAP_SECS = 300.0
SWARM_CAP_SECS = 150.0       # swarm-explorer phase (ISSUE 5)
SPILL_CAP_SECS = 120.0       # capacity-ladder phase (ISSUE 6)
CAPACITY2_CAP_SECS = 120.0   # packed/symmetry/async-drain phase (ISSUE 15)
SERVICE_CAP_SECS = 120.0     # multi-tenant service phase (ISSUE 11)
MESH_CAP_SECS = 150.0        # 8-device mesh headline phase (ISSUE 12)
LANES_CAP_SECS = 150.0       # batched-job-lanes phase (ISSUE 14)
MEMO_CAP_SECS = 150.0        # cross-job memoization phase (ISSUE 16)
SCENARIOS_CAP_SECS = 120.0   # fault-scenario phase (ISSUE 19)
LABS_CAP_SECS = 120.0        # generated-labs packing phase (ISSUE 20)
# Parent backstop beyond the child's budget.  Generous on purpose: the
# child's time checks are level-granular (a slow level can overrun
# max_secs by ~30 s, sharded.py round-3 note), the strict child floors
# its search at 45 s even when compile ate the budget, and teardown over
# the tunnel costs seconds — a kill here loses the phase's number
# entirely, so the slack must cover the worst honest overrun.
KILL_SLACK_SECS = 150.0
# Fallback budgets if calibration is unavailable (round-3 measured).
FALLBACK_EV_BUDGET = (40, 8)
CAL_CACHE = "/tmp/dslabs_bench_cal.json"
# Beam ladder (chunk/device, frontier, visited): lead rung = the round-3
# measured config; the smaller rungs are OOM fallbacks so a worker crash
# on the big config still lands a beam number.
BEAM_LADDER = [
    (8192, 1 << 19, 1 << 24),
    (1024, 1 << 18, 1 << 23),
    (64, 1 << 12, 1 << 18),
]

_T0 = time.time()

# Run directory for per-phase telemetry flight logs (tpu/telemetry.py):
# the parent hands each phase child its own flight-recorder path via
# DSLABS_BENCH_FLIGHT, so a SIGKILLed/wedged child still leaves its
# last dispatches on disk and the error JSON can name the in-flight
# dispatch instead of one scraped stderr line (the BENCH_r05 mystery).
_RUNDIR_REQUESTED = os.environ.get("DSLABS_BENCH_RUNDIR",
                                   "/tmp/dslabs_bench")
_RUNDIR_STATE = {"path": None, "substituted": False}

# Structured wedge diagnostics collected by _sub on phase failure;
# attached to the last-line JSON as "wedge_diagnostics" by _emit.
_DIAGNOSTICS = []


def _rundir() -> str:
    """The run directory, PROVEN writable.  When the requested dir
    cannot be created or written (read-only FS, permission error) the
    bench falls back to a fresh tempdir instead of silently losing
    every phase's flight log — the substitution is noted in the
    last-line JSON, and wedge diagnostics on a dead phase keep
    working (they read the flight tail from the actual dir)."""
    if _RUNDIR_STATE["path"]:
        return _RUNDIR_STATE["path"]
    path = _RUNDIR_REQUESTED
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, f".probe.{os.getpid()}")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError:
        import tempfile

        path = tempfile.mkdtemp(prefix="dslabs_bench_")
        _RUNDIR_STATE["substituted"] = True
        _hb(f"run dir {_RUNDIR_REQUESTED!r} unwritable — flight logs "
            f"fall back to {path}")
    _RUNDIR_STATE["path"] = path
    return path


def _phase_telemetry(label: str):
    """The phase child's flight recorder.  The parent's path (env)
    wins; standalone phase invocations land in the run dir."""
    from dslabs_tpu.tpu.telemetry import Telemetry

    path = os.environ.get("DSLABS_BENCH_FLIGHT")
    if not path:
        path = os.path.join(_rundir(), f"{label}.flight.jsonl")
    try:
        os.remove(path)     # stale spans must not pollute this run
    except OSError:
        pass
    # Telemetry itself degrades to RAM-only recording if even this
    # path is unwritable (summary() then carries flight_error).
    return Telemetry(flight_log=path, engine_hint=label)


def _note_wedge(label: str, message: str, watch, flight) -> None:
    """ISSUE-7 satellite (the BENCH_r05 fix): a dead phase's error
    JSON carries the child's last heartbeat AND its last
    flight-recorder spans — the in-flight dispatch included — never
    just the final scraped stderr line."""
    from dslabs_tpu.tpu import telemetry as tel_mod

    tail = list(watch.tail) if watch is not None else []
    _DIAGNOSTICS.append({
        "phase": label,
        "message": message,
        "last_heartbeat": tail[-1] if tail else None,
        "stderr_tail": tail[-3:],
        "last_spans": tel_mod.tail_records(flight, 6),
    })


def _note_phase_telemetry(result: dict, label: str, phase) -> None:
    """Collect a phase's telemetry summary under the top-level
    ``telemetry`` block (pinned by the bench-JSON schema test)."""
    t = (phase or {}).get("telemetry") if isinstance(phase, dict) \
        else None
    if not t:
        return
    result.setdefault(
        "telemetry", {"run_dir": _rundir(), "phases": {}})[
        "phases"][label] = t


def _remaining() -> float:
    return DEADLINE_SECS - (time.time() - _T0)


def _hb(msg: str) -> None:
    print(f"[bench +{time.time() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _bench_protocol():
    import dataclasses

    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

    # Two clients widen the space enough to sustain large frontiers.
    # Goals are stripped: the bench measures sustained exploration
    # throughput, and a lucky beam hitting CLIENTS_DONE mid-run would end
    # it early with a run-dependent rate.
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    return dataclasses.replace(protocol, goals={})


_PROTO_SIG = "paxos-n3-c2-w1-s3-net64-t6-v5"


def _persistent_cache():
    import jax

    from dslabs_tpu.tpu import compile_cache

    if os.environ.get("DSLABS_FORCE_CPU"):
        # The axon plugin pins jax_platforms at registration, so the
        # JAX_PLATFORMS env var alone cannot select CPU — re-pin via
        # config (same trick as tests/conftest.py).  CI and local
        # structure-validation runs use this.
        jax.config.update("jax_platforms", "cpu")
        compile_cache.setup(default_dir="/tmp/jaxcache-cpu")
    else:
        # Every phase child — the PREFLIGHT included — reuses the same
        # persistent cache (DSLABS_COMPILE_CACHE overrides the
        # location), so a warm run's preflight matmul and the search
        # programs skip XLA entirely and the 300 s compile blowout of
        # BENCH_r05 cannot recur.
        compile_cache.setup(default_dir="/tmp/jaxcache")


# --------------------------------------------------------------- children

def _preflight() -> dict:
    """Accelerator liveness probe — a WARDEN PROBE twice over: the
    child heartbeats between its boot stages (so the parent's silence
    monitor kills a wedged runtime in ~60 s, not at the phase budget),
    and the tiny matmul runs through the same dispatch boundary the
    search hot loops use (tpu/supervisor.py ``probe_device``), so a
    wedge that lets heartbeats through still surfaces as a classified,
    attributable ``DispatchTimeout`` inside this bounded subprocess
    instead of a bare hang in a 400 s search phase."""
    tel = _phase_telemetry("preflight")
    wedge = os.environ.get("DSLABS_BENCH_FAKE_WEDGE")
    if wedge == "hang":
        # Test knob, hang shape: the child goes SILENT (the true
        # BENCH_r05 wedge) — only the parent's silence kill ends it.
        # The hang happens INSIDE a telemetry span, so the flight log's
        # torn tail names the in-flight dispatch (the satellite fix).
        _hb("preflight: simulated wedge (hanging)")
        with tel.span("preflight.hang"):
            time.sleep(100000.0)
    if wedge:
        # Test knob, fast shape: the wedge raises immediately so the
        # cpu-fallback path is exercisable cheaply in CI.
        raise RuntimeError("fake TPU wedge (DSLABS_BENCH_FAKE_WEDGE)")
    _hb("preflight: boot (import + compile cache)")
    with tel.span("preflight.boot"):
        _persistent_cache()
    from dslabs_tpu.tpu.supervisor import probe_device

    _hb("preflight: probe matmul")
    with tel.span("preflight.matmul"):
        res = probe_device(deadline_secs=float(os.environ.get(
            "DSLABS_PREFLIGHT_DEADLINE_SECS", "60.0")))
    res["telemetry"] = tel.summary()
    return res


def _calibrate(max_depth: int = 7) -> dict:
    """Measure per-state valid-event occupancy on a shallow full-grid
    strict prefix; budgets = measured max + headroom (growth continues
    past the calibration depth — the spill covers the tail for strict,
    and beam counts the drops as before)."""
    import jax
    import jax.numpy as jnp

    _persistent_cache()

    from dslabs_tpu.tpu.engine import SENTINEL, timer_deliverable_mask
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    protocol = _bench_protocol()
    mesh = make_mesh(len(jax.devices()))
    search = ShardedTensorSearch(
        protocol, mesh, chunk_per_device=1024, frontier_cap=1 << 17,
        visited_cap=1 << 22, max_depth=1, strict=True)

    def stats(carry):
        cur, cur_n = carry["cur"], carry["cur_n"][0]
        states = search.unflatten_rows(cur)
        valid = jnp.arange(cur.shape[0]) < cur_n
        msgs = jnp.sum(states["net"][:, :, 0] != SENTINEL, axis=1)
        tmrs = jnp.sum(jax.vmap(jax.vmap(timer_deliverable_mask))(
            states["timers"]), axis=(1, 2))
        return (jnp.max(jnp.where(valid, msgs, 0)),
                jnp.max(jnp.where(valid, tmrs, 0)))

    jstats = jax.jit(stats)
    bm = bt = 1
    with mesh:
        carry = search._init_carry(search.initial_state())
        max_n, depth, t0 = 1, 0, time.time()
        while max_n > 0 and depth < max_depth:
            depth += 1
            n_chunks = -(-(max_n + search.n_devices - 1) // search.cpd)
            for _ in range(n_chunks):
                carry = search._chunk_step(carry)
            _, _, _, _, max_n, _ = search._sync_checks(carry, depth, t0)
            carry = search._finish_level(carry)
            m, t = (int(x) for x in jax.tree.map(jnp.asarray,
                                                 jstats(carry)))
            bm, bt = max(bm, m), max(bt, t)
    p = search.p
    # Headroom: message occupancy keeps growing past the calibration
    # depth (~1/level); timers are structurally bounded by the retry
    # re-arm pattern.  Budgets clamp to the full grid.
    return {"bm": min(bm + bm // 2 + 4, p.net_cap),
            "bt": min(bt + 2, p.n_nodes * p.timer_cap),
            "measured": [bm, bt], "depth": depth}


def _run_rung(chunk_per_device: int, frontier_cap: int, visited_cap: int,
              max_secs: float, ev_budget) -> dict:
    import jax

    _persistent_cache()

    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    tel = _phase_telemetry("rung")
    mesh = make_mesh(len(jax.devices()))
    # Warm-up depth 2, not 1: the final depth-limited level skips the
    # frontier promotion (count-only), so a depth-1 run would leave
    # _finish_level uncompiled and charge its compile to the window.
    # aot_warmup compiles the superstep/promote/init programs at
    # construction (.lower().compile(), persistent-cache backed) —
    # compile cost is measured on its own, never inside the window.
    t_c = time.time()
    search = ShardedTensorSearch(
        _bench_protocol(), mesh, chunk_per_device=chunk_per_device,
        frontier_cap=frontier_cap, visited_cap=visited_cap, max_depth=2,
        strict=False, ev_budget=ev_budget, aot_warmup=True,
        telemetry=tel)
    search.run()  # warm-up: residual compiles + runtime plumbing
    compile_secs = time.time() - t_c
    search.max_depth = 64
    search.max_secs = max_secs
    outcome = search.run()
    elapsed = max(outcome.elapsed_secs, 1e-9)
    return {
        "value": outcome.unique_states / elapsed * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        # Beam drops under their roadmap name (ISSUE 6 satellite: the
        # BENCH_r03 5.8M-drop shape is a first-class JSON field, and
        # the engine warns loudly past DSLABS_DROPPED_WARN).
        "dropped_states": outcome.dropped_states,
        "elapsed": elapsed,
        "compile_secs": round(compile_secs, 1),
        "aot_compile_secs": outcome.compile_secs,
        "levels": outcome.levels,
        "retries": outcome.retries,
        "failovers": outcome.failovers,
        "resumed_from_depth": outcome.resumed_from_depth,
        "mesh_shrinks": outcome.mesh_shrinks,
        "knob_retries": outcome.knob_retries,
        "telemetry": tel.summary(),
    }


def _run_strict(ev_budget, budget_secs: float) -> dict:
    """The drop-free HEADLINE number: a strict (exact, nothing
    truncated) BFS of the bench protocol to depth 10 — every valid event
    of every reachable state expanded, dropped=0 enforced fatally.

    ``budget_secs`` bounds the whole phase CHILD-SIDE: whatever the
    warm-up compile leaves is handed to search.max_secs, so a slow run
    lands a partial rate (TIME_EXHAUSTED) instead of dying to the
    parent's kill with nothing on stdout.

    Config notes: chunk 8192 (on one device the routing bucket holds the
    whole batch, so strict skips the in-chunk prefilter too); the
    calibrated ev_budget WINDOW-SPILLS (a state with more valid events
    re-steps its chunk at the next window — never a coverage cut); the
    final level counts fresh states without building the ~4x-over-cap
    depth-10 frontier.  DSLABS_BENCH_CKPT=1 additionally runs async
    incremental checkpoints every 2 levels (overhead-demonstration)."""
    import jax

    _persistent_cache()

    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    from dslabs_tpu.tpu.supervisor import RetryPolicy, SearchSupervisor

    t_phase = time.time()
    tel = _phase_telemetry("strict")
    mesh = make_mesh(len(jax.devices()))
    ckpt = {}
    if os.environ.get("DSLABS_BENCH_CKPT"):
        ckpt = {"checkpoint_path": "/tmp/bench_strict.ckpt",
                "checkpoint_every": 2}
    # The measured run goes through the search SUPERVISOR
    # (tpu/supervisor.py): transient dispatch errors retry with backoff
    # instead of killing the phase, and the outcome's retries /
    # failovers / resumed_from_depth counters land in the BENCH json so
    # the perf trajectory shows robustness overhead.  Ladder = sharded
    # only — a failover to the single-device engine would change what
    # the headline number measures.
    sup = SearchSupervisor(
        _bench_protocol(), ladder=("sharded",), mesh=mesh, chunk=8192,
        frontier_cap=(1 << 20) + (1 << 18), visited_cap=1 << 24,
        max_depth=2, strict=True, ev_budget=ev_budget,
        policy=RetryPolicy(max_retries=3), aot_warmup=True,
        telemetry=tel, **ckpt)
    t_c = time.time()
    sup.run()  # warm-up: AOT at engine build + residual compiles
    compile_secs = time.time() - t_c
    sup.max_depth = 10
    sup.max_secs = max(45.0, budget_secs - (time.time() - t_phase))
    t0 = time.time()
    outcome = sup.run()
    return {
        "value": outcome.unique_states / max(outcome.elapsed_secs, 1e-9)
        * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        "dropped_states": outcome.dropped_states,
        "elapsed": time.time() - t0,
        "compile_secs": round(compile_secs, 1),
        "aot_compile_secs": outcome.compile_secs,
        "levels": outcome.levels,
        "retries": outcome.retries,
        "failovers": outcome.failovers,
        "resumed_from_depth": outcome.resumed_from_depth,
        "abandoned_threads": outcome.abandoned_threads,
        # Elastic-mesh resilience counters (ISSUE 9): how much mesh /
        # knob degradation this number absorbed — `telemetry compare`
        # flags a run that suddenly needs them (resilience regression).
        "mesh_shrinks": outcome.mesh_shrinks,
        "knob_retries": outcome.knob_retries,
        "mesh_width": outcome.mesh_width,
        "telemetry": tel.summary(),
    }


def _run_mesh(budget_secs: float) -> dict:
    """The 8-device mesh headline phase (ISSUE 12): a strict BFS whose
    frontier, visited table, and expansion run owner-sharded over a
    width-``DSLABS_MESH_WIDTH`` (default 8) mesh with the fused
    in-superstep row exchange — the configuration ROADMAP #1 promotes
    to the headline.  On a box with >= width real accelerators the
    full paxos bench protocol runs on them; otherwise the phase runs
    on the CPU VIRTUAL mesh (tagged ``virtual_cpu_mesh``) with the
    lab1 workload the cpu-fallback phase already benches — an honest,
    always-reports mesh number instead of a skipped phase.

    The JSON carries what the acceptance criteria read: ``mesh_width``,
    aggregate ``skew`` (finite — derived from the per-level per-device
    lanes, which ride on ``levels``), and the recovery counters
    (``mesh_shrinks``/``knob_retries`` must be 0 for the number to be
    trusted as a full-width rate)."""
    import dataclasses

    width = int(os.environ.get("DSLABS_MESH_WIDTH", "8") or "8")
    # The headline benches the balanced mesh (ISSUE 18): root-fanout
    # seeding plus chunk-granular stealing at level boundaries.  An
    # explicit DSLABS_MESH_STEAL_THRESHOLD (including "0" = off, the
    # parity oracle) wins.
    os.environ.setdefault("DSLABS_MESH_STEAL_THRESHOLD", "1.5")
    _persistent_cache()
    import jax

    from dslabs_tpu.tpu.sharded import make_mesh
    from dslabs_tpu.tpu.supervisor import RetryPolicy, SearchSupervisor

    t_phase = time.time()
    tel = _phase_telemetry("mesh")
    mesh = make_mesh(width)
    platform = mesh.devices.flat[0].platform
    virtual = platform == "cpu"
    if virtual:
        # The GENERATED lab1 spec (identical state space to the hand
        # twin — 150 unique / 831 explored at depth 6) so the packed
        # wire engages: the hand protocol derives the identity codec
        # and would bench raw lanes (ISSUE 18a).
        from dslabs_tpu.tpu.specs import clientserver_spec

        proto = dataclasses.replace(
            clientserver_spec(3, 4).compile(), goals={})
        config = f"lab1-clientserver c3-w4 strict mesh x{width}"
        kw = dict(chunk=256, frontier_cap=1 << 13,
                  visited_cap=1 << 17)
        depth = int(os.environ.get("DSLABS_MESH_DEPTH", "12"))
    else:
        proto = _bench_protocol()
        config = f"lab3-paxos strict mesh x{width}"
        kw = dict(chunk=4096, frontier_cap=1 << 18,
                  visited_cap=1 << 22, ev_budget=FALLBACK_EV_BUDGET)
        depth = int(os.environ.get("DSLABS_MESH_DEPTH", "10"))
    sup = SearchSupervisor(
        proto, ladder=("sharded",), mesh=mesh, max_depth=2,
        strict=True, policy=RetryPolicy(max_retries=3),
        aot_warmup=True, telemetry=tel, **kw)
    t_c = time.time()
    sup.run()   # warm-up: AOT + residual compiles, outside the window
    compile_secs = time.time() - t_c
    sup.max_depth = depth
    # 90 s of measured search is plenty for a stable rate; the floor
    # keeps a compile-heavy cold run landing a partial number.
    sup.max_secs = max(20.0, min(
        budget_secs - (time.time() - t_phase), 90.0))
    t0 = time.time()
    outcome = sup.run()
    elapsed = max(time.time() - t0, 1e-9)
    levels = outcome.levels or []
    imb = [lv["skew"]["explored"]["imbalance"] for lv in levels
           if lv.get("skew")]
    cv = [lv["skew"]["explored"]["cv"] for lv in levels
          if lv.get("skew")]
    post = [lv["skew"]["frontier_post_steal"]["imbalance"]
            for lv in levels
            if lv.get("skew", {}).get("frontier_post_steal")]
    stolen = sum(int(lv["steal"]["moved"]) for lv in levels
                 if lv.get("steal"))
    skew = {
        "imbalance_max": round(max(imb), 4) if imb else 1.0,
        "imbalance_mean": round(sum(imb) / len(imb), 4) if imb else 1.0,
        "cv_max": round(max(cv), 4) if cv else 0.0,
        "levels_measured": len(imb),
        # Post-rebalance frontier skew (ISSUE 18c): the imbalance the
        # NEXT level actually expands with, after fanout + stealing.
        "imbalance_max_post_steal": round(max(post), 4) if post else
        (round(max(imb), 4) if imb else 1.0),
        "steal_levels": len(post),
        "stolen_rows": stolen,
    }
    # Estimated ICI wire bytes per exchanged state (ISSUE 18a): the
    # packed row width the all_to_all actually ships (the engine stamps
    # it on the outcome) vs the raw-lane width, plus the 16-byte
    # fingerprint key that rides beside every row either way.  The
    # ledger guards wire_bytes_per_state (telemetry compare, rc 1 on a
    # rise: the codec fell back to identity).
    wire = {
        "wire_bytes_per_state": int(outcome.bytes_per_state or 0),
        "wire_bytes_per_state_raw": int(
            outcome.bytes_per_state_unpacked or 0),
        "key_bytes_per_state": 16,
        "pack_ratio": float(outcome.pack_ratio or 1.0),
    }
    return {
        "value": outcome.unique_states / elapsed * 60.0,
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "depth": outcome.depth,
        "end": outcome.end_condition,
        "dropped": outcome.dropped,
        "dropped_states": outcome.dropped_states,
        "elapsed": round(elapsed, 2),
        "compile_secs": round(compile_secs, 1),
        "aot_compile_secs": outcome.compile_secs,
        "config": config,
        "platform": platform,
        "mesh_width": width,
        "virtual_cpu_mesh": virtual,
        "skew": skew,
        # Top-level copies the ledger guards read (telemetry
        # compare_ledger: mesh:wire_bytes_per_state rises or
        # mesh:imbalance_max rises past threshold -> rc 1).
        "imbalance_max": skew["imbalance_max_post_steal"],
        "wire": wire,
        "levels": levels,
        "retries": outcome.retries,
        "failovers": outcome.failovers,
        "resumed_from_depth": outcome.resumed_from_depth,
        "mesh_shrinks": outcome.mesh_shrinks,
        "knob_retries": outcome.knob_retries,
        "telemetry": tel.summary(),
    }


def _cpu_fallback(budget_secs: float) -> dict:
    """Wedged-TPU fallback (ISSUE 1): a bounded strict lab1 BFS on the
    CPU backend, measured TWICE on the identical protocol/depth — the
    device-resident wave loop (engine.py ``run()``, this PR's hot path:
    donated visited table + frontier, scalar-only syncs) and the legacy
    host-dedup loop (``run_host()``, verbatim the pre-PR ``tensor_bfs``
    single-chip hot loop) — so a wedged round lands a real, comparable
    before/after states/min pair instead of 0.0.

    On the CPU backend both loops share the same XLA expand (the
    dominant cost — there is no device->host tunnel to win back here);
    the pair is the honest apples-to-apples record, and the device
    loop's structural win (scalar-only transfers, in-place donated
    carry) shows up fully on the tunnelled TPU runtime."""
    import dataclasses

    os.environ["DSLABS_FORCE_CPU"] = "1"
    _persistent_cache()

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    t_phase = time.time()
    tel = _phase_telemetry("cpu-fallback")
    proto = dataclasses.replace(
        make_clientserver_protocol(n_clients=3, w=4, net_cap=32),
        goals={})
    depth = int(os.environ.get("DSLABS_FALLBACK_DEPTH", "15"))

    def run_one(use_host: bool) -> dict:
        search = TensorSearch(proto, chunk=2048, frontier_cap=1 << 17,
                              max_depth=2, telemetry=tel)
        runner = search.run_host if use_host else search.run
        t_c = time.time()
        runner()            # warm-up: compile outside the measured window
        compile_secs = time.time() - t_c
        search.max_depth = depth
        search.max_secs = max(20.0, budget_secs / 3)
        t0 = time.time()
        out = runner()
        dt = max(time.time() - t0, 1e-9)
        return {"value": out.unique_states / dt * 60.0,
                "unique": out.unique_states,
                "explored": out.states_explored,
                "depth": out.depth, "end": out.end_condition,
                "elapsed": round(dt, 2),
                "compile_secs": round(compile_secs, 1),
                "retries": out.retries, "failovers": out.failovers,
                "resumed_from_depth": out.resumed_from_depth}

    device = run_one(use_host=False)
    legacy = run_one(use_host=True)
    return {
        "backend": "cpu-fallback",
        "config": f"lab1-clientserver c3 w4 strict depth<={depth}",
        **device,
        "legacy": legacy,
        "speedup_vs_legacy": round(
            device["value"] / max(legacy["value"], 1e-9), 2),
        "total_secs": round(time.time() - t_phase, 1),
        "telemetry": tel.summary(),
    }


def _run_swarm(budget_secs: float) -> dict:
    """Swarm-explorer throughput phase (ISSUE 5, tpu/swarm.py): a
    diversified random-walk fleet over the full mesh on the bench
    protocol, reporting walkers/sec, unique-states/min, and the
    deepest depth reached — the deep-probe half of the portfolio the
    strict/beam BFS phases cannot measure.  Same always-reports
    guarantees as every phase: child-side time bound, heartbeats on
    stderr, one JSON line on stdout."""
    import jax

    _persistent_cache()

    from dslabs_tpu.tpu.sharded import make_mesh
    from dslabs_tpu.tpu.swarm import SwarmSearch

    t_phase = time.time()
    tel = _phase_telemetry("swarm")
    mesh = make_mesh(len(jax.devices()))
    sw = SwarmSearch(
        _bench_protocol(), mesh=mesh,
        walkers_per_device=int(os.environ.get("DSLABS_SWARM_WALKERS",
                                              "256")),
        max_steps=int(os.environ.get("DSLABS_SWARM_STEPS", "128")),
        steps_per_round=64, seed=0, visited_cap=1 << 22)
    _hb("swarm: fleet built, compiling round program")
    tel.attach(sw)
    sw.max_secs = max(20.0, budget_secs - (time.time() - t_phase) - 10)
    outcome = sw.run()
    sd = outcome.swarm or {}
    return {
        "value": sd.get("unique_per_min", 0.0),
        "walkers_per_sec": sd.get("walkers_per_sec", 0.0),
        "unique_per_min": sd.get("unique_per_min", 0.0),
        "deepest": sd.get("deepest", outcome.depth),
        "unique": outcome.unique_states,
        "explored": outcome.states_explored,
        "end": outcome.end_condition,
        "rounds": sd.get("rounds", 0),
        "restarts": outcome.walker_restarts,
        "overflow_restarts": outcome.swarm_overflow,
        "vis_over": outcome.visited_overflow,
        "elapsed": round(outcome.elapsed_secs, 2),
        "compile_secs": outcome.compile_secs,
        "telemetry": tel.summary(),
    }


def _run_spill(budget_secs: float) -> dict:
    """Capacity-ladder phase (ISSUE 6, tpu/spill.py): a strict lab1
    BFS measured twice on the identical protocol/depth — uncapped,
    then with the device visited table capped at ~1/8 of the measured
    unique-state count and the host-RAM spill tier enabled — so the
    round records what graceful degradation under HBM exhaustion
    costs: states/min both ways, exact unique/explored parity flag,
    spill counters, and ``dropped_states`` (must be 0 — the whole
    point).  Same always-reports guarantees as every phase: child-side
    time bound, heartbeats on stderr, one JSON line on stdout."""
    import dataclasses
    import math

    _persistent_cache()

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.clientserver import \
        make_clientserver_protocol

    t_phase = time.time()
    tel = _phase_telemetry("spill")
    proto = dataclasses.replace(
        make_clientserver_protocol(n_clients=3, w=4), goals={})
    depth = int(os.environ.get("DSLABS_SPILL_DEPTH", "11"))

    def run_one(visited_cap, spill, chunk):
        search = TensorSearch(proto, chunk=chunk, frontier_cap=1 << 15,
                              max_depth=2, visited_cap=visited_cap,
                              spill=spill, telemetry=tel)
        t_c = time.time()
        search.run()          # warm-up: compile outside the window
        compile_secs = time.time() - t_c
        search.max_depth = depth
        search.max_secs = max(
            20.0, (budget_secs - (time.time() - t_phase)) / 2)
        t0 = time.time()
        out = search.run()
        return out, max(time.time() - t0, 1e-9), compile_secs

    _hb("spill: uncapped reference run")
    un, dt_u, cs_u = run_one(1 << 20, False, 2048)
    cap = 1 << max(3, int(math.floor(
        math.log2(max(un.unique_states // 8, 8)))))
    _hb(f"spill: capped run (visited_cap {cap} ~ "
        f"{cap / max(un.unique_states, 1):.2f} of "
        f"{un.unique_states} states)")
    sp, dt_s, cs_s = run_one(cap, True, 16)
    parity = (un.end_condition == sp.end_condition
              and un.unique_states == sp.unique_states
              and un.states_explored == sp.states_explored)
    return {
        "value": sp.unique_states / dt_s * 60.0,
        "uncapped_per_min": round(un.unique_states / dt_u * 60.0, 1),
        "visited_cap": cap,
        "capped_fraction": round(cap / max(un.unique_states, 1), 4),
        "end": sp.end_condition, "depth": sp.depth,
        "unique": sp.unique_states, "explored": sp.states_explored,
        "exact_parity": parity,
        "spilled_keys": sp.spilled_keys,
        "host_tier_hits": sp.host_tier_hits,
        "respilled_frontier": sp.respilled_frontier,
        "dropped_states": sp.dropped_states,
        "compile_secs": round(cs_u + cs_s, 1),
        "total_secs": round(time.time() - t_phase, 1),
        "telemetry": tel.summary(),
    }


def _run_capacity2(budget_secs: float) -> dict:
    """Capacity round 2 phase (ISSUE 15, tpu/packing.py /
    tpu/symmetry.py / tpu/spill.py async gear): on the GENERATED lab1
    spec (domain-declared, so the packed frontier encoding engages) —
    bytes_per_state packed vs unpacked, exact-parity flag, and
    packed-path states/min; a packed 1/8-table spill run for the async
    drain's overlap ratio (host drain wall hidden behind device
    compute); and the symmetry quotient on the generated paxos spec
    (canonical vs raw unique counts, verdict parity).  The ledger's
    ``capacity:bytes_per_state`` guard compares this phase across
    rounds (a rise past threshold = rc 1).  Same always-reports
    guarantees as every phase."""
    import dataclasses
    import math

    _persistent_cache()

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.specs import clientserver_spec, paxos_spec

    t_phase = time.time()
    tel = _phase_telemetry("capacity2")
    cs = clientserver_spec(3, 4).compile()
    proto = dataclasses.replace(
        cs, goals={}, prunes={"DONE": cs.goals["CLIENTS_DONE"]})
    depth = int(os.environ.get("DSLABS_CAPACITY2_DEPTH", "9"))

    def run_one(packed, spill=False, visited_cap=1 << 20, chunk=2048):
        # NOTE: engine reuse (warm-up then measure) is safe in spill
        # mode since SpillManager.reset_run — the tier no longer leaks
        # across runs.
        search = TensorSearch(proto, chunk=chunk, frontier_cap=1 << 15,
                              max_depth=2, visited_cap=visited_cap,
                              packed=packed, spill=spill,
                              telemetry=tel)
        t_c = time.time()
        search.run()          # warm-up: compile outside the window
        compile_secs = time.time() - t_c
        search.max_depth = depth
        search.max_secs = max(
            15.0, (budget_secs - (time.time() - t_phase)) / 3)
        t0 = time.time()
        out = search.run()
        return out, max(time.time() - t0, 1e-9), compile_secs

    _hb("capacity2: unpacked reference run")
    un, dt_u, cs_u = run_one(False)
    _hb("capacity2: packed run")
    pkd, dt_p, cs_p = run_one(True)
    parity = (un.end_condition == pkd.end_condition
              and un.unique_states == pkd.unique_states
              and un.states_explored == pkd.states_explored)
    # Floor 256: one chunk's unique successors must fit an EMPTY
    # table (the spill contract's hard minimum) — tiny smoke depths
    # would otherwise derive a cap below chunk * mean-events.
    cap = 1 << max(8, int(math.floor(
        math.log2(max(pkd.unique_states // 8, 8)))))
    _hb(f"capacity2: packed async-spill run (visited_cap {cap})")
    sp, _dt_s, cs_s = run_one(True, spill=True, visited_cap=cap,
                              chunk=16)
    drain_ms = sp.spill_drain_ms
    overlap_ratio = (round(max(0, drain_ms - sp.spill_wait_ms)
                           / drain_ms, 4) if drain_ms > 0 else 0.0)
    # Symmetry quotient: canonical vs raw unique counts on the
    # generated single-decree paxos spec (reduction is opt-in — this
    # is the measured win, not a default behavior change).
    px = paxos_spec(3).compile()
    pxp = dataclasses.replace(px, goals={},
                              prunes={"D": px.goals["DECIDED"]})
    _hb("capacity2: symmetry quotient (paxos raw vs canonical)")
    raw = TensorSearch(pxp, chunk=256, visited_cap=1 << 14,
                       telemetry=tel).run()
    sym = TensorSearch(pxp, chunk=256, visited_cap=1 << 14,
                       symmetry=True, telemetry=tel).run()
    return {
        "value": round(pkd.unique_states / dt_p * 60.0, 1),
        "unpacked_per_min": round(un.unique_states / dt_u * 60.0, 1),
        "bytes_per_state": pkd.bytes_per_state,
        "bytes_per_state_unpacked": un.bytes_per_state,
        "pack_ratio": pkd.pack_ratio,
        "exact_parity": parity,
        "end": pkd.end_condition, "depth": pkd.depth,
        "unique": pkd.unique_states, "explored": pkd.states_explored,
        "spill_visited_cap": cap,
        "spill_exact_parity": (sp.unique_states == pkd.unique_states
                               and sp.states_explored
                               == pkd.states_explored),
        "spill_drain_ms": drain_ms,
        "spill_wait_ms": sp.spill_wait_ms,
        "spill_overlap_ratio": overlap_ratio,
        "dropped_states": sp.dropped_states,
        "symmetry": {
            "raw_unique": raw.unique_states,
            "canonical_unique": sym.unique_states,
            "quotient": round(raw.unique_states
                              / max(sym.unique_states, 1), 3),
            "verdict_parity": raw.end_condition == sym.end_condition,
            "perms": sym.symmetry_perms},
        "compile_secs": round(cs_u + cs_p + cs_s, 1),
        "total_secs": round(time.time() - t_phase, 1),
        "telemetry": tel.summary(),
    }


def _run_service(budget_secs: float) -> dict:
    """Checking-as-a-service phase (ISSUE 11, dslabs_tpu/service/): a
    multi-tenant drain — three tenants submit small exhaustive
    pingpong jobs through the admission gate into the bounded journal
    queue, the DRR scheduler runs each as its own warden fault domain
    — reporting PER-TENANT throughput and the fairness index
    (max/mean verdicts-per-tenant-budget; `telemetry compare` flags a
    rise past the threshold as a regression).  Same always-reports
    guarantees as every phase: child-side time bound, heartbeats on
    stderr, one JSON line on stdout."""
    import tempfile

    _persistent_cache()

    from dslabs_tpu.service import CheckServer

    t_phase = time.time()
    root = tempfile.mkdtemp(prefix="service-", dir=_rundir())
    tenants = ("alice", "bob", "carol")
    jobs_per = max(1, int(os.environ.get("DSLABS_SERVICE_BENCH_JOBS",
                                         "2") or "2"))
    # Warden job children are grandchildren of the bench parent:
    # _persistent_cache() only touches THIS process's jax config, so
    # hand them the shared cache dir explicitly (same resolution as
    # _persistent_cache) or every job pays a cold XLA build.
    cache_dir = os.environ.get("DSLABS_COMPILE_CACHE") or (
        "/tmp/jaxcache-cpu" if os.environ.get("DSLABS_FORCE_CPU")
        else "/tmp/jaxcache")
    srv = CheckServer(
        root, workers=2, queue_cap=max(8, 3 * jobs_per + 1),
        elastic=False, env={"DSLABS_COMPILE_CACHE": cache_dir})
    rejected = 0
    for j in range(jobs_per):
        for t in tenants:
            res = srv.submit(
                factory="dslabs_tpu.tpu.protocols.pingpong:"
                        "make_exhaustive_pingpong",
                factory_kwargs={"workload_size": 2}, tenant=t,
                chunk=64, frontier_cap=1 << 8, visited_cap=1 << 12,
                max_secs=30.0)
            if not res.get("accepted"):
                rejected += 1
    _hb(f"service: {3 * jobs_per} jobs submitted "
        f"({rejected} rejected), draining")
    summary = srv.drain(
        max_secs=max(20.0, budget_secs - (time.time() - t_phase) - 10))
    srv.close()
    return {
        "value": summary["verdicts_per_min"],
        "jobs": summary["jobs"],
        "completed": summary["completed"],
        "failed": summary["failed"],
        "rejected": rejected,
        "fairness_index": summary["fairness_index"],
        # The per-tenant cost ledger (ISSUE 13, tpu/tracing.py):
        # device-seconds / dispatches / compile split per tenant, plus
        # the aggregate cost-per-unique-state the ledger compare
        # tracks for regressions (telemetry.compare_ledger).
        "cost_per_unique": summary.get("cost_per_unique"),
        "device_secs": summary.get("device_secs"),
        "costs": summary.get("costs"),
        "per_tenant": {
            t: {"verdicts": s["verdicts"],
                "verdicts_per_min": s["verdicts_per_min"],
                "budget_spent": s["budget_spent"]}
            for t, s in summary["per_tenant"].items()},
        "queue": summary["queue"],
        "total_secs": round(time.time() - t_phase, 1),
    }


def _run_lanes(budget_secs: float) -> dict:
    """Batched job lanes phase (ISSUE 14, tpu/lanes.py): FOUR tenants
    each submit one identical small exhaustive job, drained twice —
    solo (lanes off, the 4-solo baseline) and as one 4-lane batch —
    and the phase reports aggregate states/min plus
    **dispatches-per-job** for both, the amortisation headline the
    ledger's ``service:dispatches_per_job`` / ``lanes:occupancy``
    compare guards track (regression => rc 1).  Verdicts are asserted
    bit-identical between the two drains (lane parity is a bench
    invariant, not just a test).  Same always-reports guarantees as
    every phase."""
    import tempfile

    _persistent_cache()

    from dslabs_tpu.service import CheckServer

    t_phase = time.time()
    tenants = ("alice", "bob", "carol", "dave")
    cache_dir = os.environ.get("DSLABS_COMPILE_CACHE") or (
        "/tmp/jaxcache-cpu" if os.environ.get("DSLABS_FORCE_CPU")
        else "/tmp/jaxcache")

    def _drain(lanes: int) -> dict:
        root = tempfile.mkdtemp(prefix=f"lanes{lanes}-",
                                dir=_rundir())
        srv = CheckServer(
            root, workers=1, queue_cap=len(tenants) + 4,
            elastic=False, admission=False, lanes=lanes,
            env={"DSLABS_COMPILE_CACHE": cache_dir})
        for t in tenants:
            srv.submit(
                factory="dslabs_tpu.tpu.protocols.pingpong:"
                        "make_exhaustive_pingpong",
                factory_kwargs={"workload_size": 2}, tenant=t,
                chunk=64, frontier_cap=1 << 8, visited_cap=1 << 12,
                max_secs=30.0)
        left = budget_secs - (time.time() - t_phase) - 10
        summary = srv.drain(max_secs=max(20.0, left / 2))
        srv.close()
        return summary

    _hb("lanes: 4-solo baseline drain")
    solo = _drain(0)
    _hb(f"lanes: solo dpj={solo.get('dispatches_per_job')}; "
        "4-lane batched drain")
    lane = _drain(4)
    wall = max(lane.get("wall_secs", 0.0), 1e-9)
    explored = sum(int(r.get("explored", 0) or 0)
                   for r in lane.get("results", ()))
    key = ("tenant", "end", "unique", "explored", "depth")
    sv = sorted(tuple(r.get(k) for k in key)
                for r in solo.get("results", ()))
    lv = sorted(tuple(r.get(k) for k in key)
                for r in lane.get("results", ()))
    dpj = lane.get("dispatches_per_job")
    solo_dpj = solo.get("dispatches_per_job")
    return {
        # aggregate throughput of the batched drain — the phase value
        # the ledger tracks alongside the amortisation guards.
        "value": round(explored / wall * 60.0, 1),
        "jobs": lane.get("jobs"),
        "completed": lane.get("completed"),
        "failed": lane.get("failed"),
        "lanes": 4,
        "dispatches_per_job": dpj,
        "solo_dispatches_per_job": solo_dpj,
        "dpj_ratio": (round(dpj / solo_dpj, 3)
                      if dpj and solo_dpj else None),
        "occupancy": (lane.get("lanes") or {}).get("mean_occupancy"),
        "swaps": (lane.get("lanes") or {}).get("swaps"),
        "evicted": (lane.get("lanes") or {}).get("evicted"),
        "verdict_parity": sv == lv,
        "fairness_index": lane.get("fairness_index"),
        "cost_per_unique": lane.get("cost_per_unique"),
        "total_secs": round(time.time() - t_phase, 1),
    }


_MEMO_CHAIN_SRC = """\
from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                     ProtocolSpec, TimerType)


def make_chain():
    spec = ProtocolSpec(
        "memo-bench-chain",
        nodes=[NodeKind("proc", 1, (Field("x", init=0, hi=4),))],
        messages=[MessageType("S1", ()), MessageType("S2", ()),
                  MessageType("S3", ())],
        timers=[TimerType("TICK", (), 10, 10)],
        net_cap=4, timer_cap=1)

    @spec.on("proc", "S1")
    def h1(ctx, m):
        ctx.put("x", 1)
        ctx.send("S2", 0)

    @spec.on("proc", "S2")
    def h2(ctx, m):
        ctx.put("x", 2)
        ctx.send("S3", 0)

    @spec.on("proc", "S3")
    def h3(ctx, m):
        ctx.put("x", %(final)d)

    spec.initial_messages.append(("S1", 0, 0, {}))

    def no_four(v):
        return v.get("proc", 0, "x") != 4

    spec.invariants["NO_FOUR"] = no_four
    return spec.compile()
"""


def _run_memo(budget_secs: float) -> dict:
    """Cross-job memoization phase (ISSUE 16, service/memo.py): one
    pingpong job is checked COLD, resubmitted identically (verdict-
    cache hit), resubmitted after only the depth budget grew (warm
    start from the archived tier), and a one-handler spec edit is
    re-checked incrementally — reporting device-seconds per reuse
    state, the hit_rate the ledger's ``memo:hit_rate`` guard tracks
    (drop past the threshold => rc 1), levels_skipped, and
    device_secs_saved.  Same always-reports guarantees as every
    phase."""
    import tempfile

    _persistent_cache()

    from dslabs_tpu.service import CheckServer

    t_phase = time.time()
    cache_dir = os.environ.get("DSLABS_COMPILE_CACHE") or (
        "/tmp/jaxcache-cpu" if os.environ.get("DSLABS_FORCE_CPU")
        else "/tmp/jaxcache")
    specs_dir = tempfile.mkdtemp(prefix="memo-specs-", dir=_rundir())

    def _cost(root, tenant):
        path = os.path.join(root, "COSTS.jsonl")
        secs = 0.0
        try:
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("tenant") == tenant:
                        secs += float(rec.get("device_secs", 0.0)
                                      or 0.0)
        except OSError:
            pass
        return round(secs, 4)

    pp = dict(factory="dslabs_tpu.tpu.protocols.pingpong:"
                      "make_exhaustive_pingpong",
              factory_kwargs={"workload_size": 2}, chunk=64,
              frontier_cap=1 << 8, visited_cap=1 << 12)
    root = tempfile.mkdtemp(prefix="memo-", dir=_rundir())
    srv = CheckServer(root, workers=1, elastic=False,
                      extra_sys_path=[specs_dir],
                      env={"DSLABS_COMPILE_CACHE": cache_dir})
    # Stage 1+2: cold, then the exact-key hit.
    srv.submit(tenant="cold", **pp)
    srv.drain(max_secs=max(20.0, budget_secs / 4))
    _hb("memo: cold verdict landed, resubmitting identical job")
    srv.submit(tenant="hit", **pp)
    # Stage 3: only the budget changed — warm start from the tier.
    with open(os.path.join(specs_dir, "memo_bench_chain.py"),
              "w") as f:
        f.write(_MEMO_CHAIN_SRC % {"final": 3})
    chain = dict(factory="memo_bench_chain:make_chain", chunk=64,
                 frontier_cap=1 << 8, visited_cap=1 << 12)
    srv.submit(tenant="chain_cold", max_depth=2, **chain)
    srv.drain(max_secs=max(20.0, budget_secs / 4))
    _hb("memo: chain depth-2 archived, growing budget (warm start)")
    srv.submit(tenant="warm", **chain)
    srv.drain(max_secs=max(20.0, budget_secs / 4))
    # Stage 4: the one-handler edit — incremental re-check.
    with open(os.path.join(specs_dir, "memo_bench_chain.py"),
              "w") as f:
        f.write(_MEMO_CHAIN_SRC % {"final": 4})
    _hb("memo: one-handler edit, incremental re-check")
    srv.submit(tenant="incr", **chain)
    summary = srv.drain(
        max_secs=max(20.0, budget_secs - (time.time() - t_phase) - 5))
    srv.close()
    memo = summary.get("memo", {})
    done = [r for r in srv.results if r.get("status") == "done"]
    wall = max(time.time() - t_phase, 1e-9)
    return {
        # verdicts/min across all reuse states — the phase value the
        # ledger tracks beside the hit_rate guard.
        "value": round(len(done) / wall * 60.0, 1),
        "jobs": summary.get("jobs"),
        "completed": summary.get("completed"),
        "failed": summary.get("failed"),
        "hit_rate": memo.get("hit_rate"),
        "hits": memo.get("hits"),
        "warm_starts": memo.get("warm_starts"),
        "incremental": memo.get("incremental"),
        "levels_skipped": memo.get("levels_skipped"),
        "device_secs_saved": memo.get("device_secs_saved"),
        "device_secs": {
            "cold": _cost(root, "cold"),
            "hit": _cost(root, "hit"),
            "warm": _cost(root, "warm"),
            "incremental": _cost(root, "incr")},
        "total_secs": round(time.time() - t_phase, 1),
    }


def _run_scenarios(budget_secs: float) -> dict:
    """Fault-scenario phase (ISSUE 19, tpu/faults.py): on the generated
    single-decree paxos spec — states/min with the partition fault
    lanes ON (paxos_partition_spec: cut/heal as model events) vs the
    plain fault-free spec OFF, the fault-event share of the explored
    space, and the ``verdict_parity`` flag the ledger's
    ``scenarios:verdict_parity`` guard pins: a ZERO-BUDGET FaultModel
    (constant controller lanes, no valid fault events) must land the
    exact fault-free verdict/explored/unique — the overhead-guard
    invariant every scenario rides on.  Same always-reports guarantees
    as every phase."""
    import dataclasses

    _persistent_cache()

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.faults import FaultModel, Partition
    from dslabs_tpu.tpu.specs import paxos_partition_spec, paxos_spec

    t_phase = time.time()
    tel = _phase_telemetry("scenarios")

    def _pruned(p):
        return dataclasses.replace(
            p, goals={}, prunes=dict(p.goals),
            invariants=dict(p.invariants))

    def run_one(proto):
        search = TensorSearch(proto, chunk=256, frontier_cap=1 << 14,
                              visited_cap=1 << 17, telemetry=tel)
        search.run()          # warm-up: compile outside the window
        t0 = time.time()
        out = search.run()
        return out, max(time.time() - t0, 1e-9)

    _hb("scenarios: fault-free baseline (plain paxos)")
    base, dt_b = run_one(_pruned(paxos_spec(3).compile()))
    _hb("scenarios: zero-budget FaultModel (overhead guard)")
    fm0 = FaultModel(partition=Partition(
        blocks=(("proposer",), ("acceptor",)), max_eras=0))
    zb, _dt_z = run_one(_pruned(paxos_spec(3, fault=fm0).compile()))
    parity = (zb.end_condition == base.end_condition
              and zb.states_explored == base.states_explored
              and zb.unique_states == base.unique_states)
    _hb("scenarios: partition cut/heal scenario (fault lanes on)")
    sc, dt_s = run_one(_pruned(paxos_partition_spec(3).compile()))
    share = (round(sc.fault_events / sc.states_explored, 4)
             if sc.states_explored else 0.0)
    return {
        "value": round(sc.states_explored / dt_s * 60.0, 1),
        "rate_off": round(base.states_explored / dt_b * 60.0, 1),
        "verdict_parity": int(parity),
        "fault_event_share": share,
        "end": sc.end_condition, "depth": sc.depth,
        "unique": sc.unique_states, "explored": sc.states_explored,
        "fault_events": sc.fault_events,
        "partition_events": sc.partition_events,
        "base": {"end": base.end_condition,
                 "unique": base.unique_states,
                 "explored": base.states_explored},
        "total_secs": round(time.time() - t_phase, 1),
        "telemetry": tel.summary(),
    }


def _run_labs(budget_secs: float) -> dict:
    """Generated-labs packing phase (ISSUE 20, tpu/specs_lab3.py +
    tpu/specs_lab4.py): the shipped lab3/lab4 protocols are COMPILED
    from ProtocolSpec now, so their Field/Slots domain declarations
    reach the bit-packer (tpu/packing.py) — the hand twins declared
    nothing and derived identity.  Reports packed bytes-per-state for
    each generated lab spec plus the summed ``bytes_per_state`` the
    ledger's ``labs:bytes_per_state`` guard pins (a rise = domains
    stopped reaching the packer), the minimum pack ratio across the
    set (acceptance floor: >= 2x), and states/min on a short search of
    the generated paxos spec as the phase value."""
    import dataclasses

    _persistent_cache()

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.packing import derive_packing
    from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol
    from dslabs_tpu.tpu.specs_lab4 import (make_join_protocol,
                                           make_shardstore_multi_protocol,
                                           make_shardstore_protocol,
                                           make_shardstore_tx_protocol)

    t_phase = time.time()
    tel = _phase_telemetry("labs")
    specs = [
        ("lab3_paxos", make_paxos_protocol()),
        ("lab4_join", make_join_protocol(1)),
        ("lab4_shardstore", make_shardstore_protocol([1, 1])),
        ("lab4_tx", make_shardstore_tx_protocol(1)),
        ("lab4_multi", make_shardstore_multi_protocol()),
    ]
    per_lab, total_packed, total_raw, min_ratio = {}, 0, 0, None
    for label, proto in specs:
        _hb(f"labs: derive packing for {label} ({proto.name})")
        eng = TensorSearch(dataclasses.replace(proto, goals={}),
                           chunk=64)
        pk = eng._pk or derive_packing(eng.p, eng.lanes)
        per_lab[label] = {
            "bytes_per_state": pk.bytes_per_state,
            "bytes_per_state_unpacked": pk.bytes_per_state_unpacked,
            "pack_ratio": round(pk.pack_ratio, 2),
        }
        total_packed += pk.bytes_per_state
        total_raw += pk.bytes_per_state_unpacked
        r = pk.pack_ratio
        min_ratio = r if min_ratio is None else min(min_ratio, r)
    _hb("labs: states/min on the generated paxos spec")
    # Depth 6 keeps compile + two runs (warm-up, timed) inside the
    # phase cap on the CPU fallback; the rate, not the space, is the
    # phase value.
    proto = dataclasses.replace(make_paxos_protocol(), goals={})
    search = TensorSearch(proto, chunk=256, frontier_cap=1 << 12,
                          visited_cap=1 << 16, max_depth=6,
                          telemetry=tel)
    search.run()              # warm-up: compile outside the window
    t0 = time.time()
    out = search.run()
    dt = max(time.time() - t0, 1e-9)
    return {
        "value": round(out.states_explored / dt * 60.0, 1),
        "bytes_per_state": total_packed,
        "bytes_per_state_unpacked": total_raw,
        "min_pack_ratio": round(min_ratio, 2),
        "labs": per_lab,
        "end": out.end_condition, "depth": out.depth,
        "unique": out.unique_states, "explored": out.states_explored,
        "total_secs": round(time.time() - t_phase, 1),
        "telemetry": tel.summary(),
    }


# ----------------------------------------------------------------- parent

_CURRENT_CHILD = None     # live phase Popen, killed by the signal handler


def _sub(args, child_budget: float, label: str,
         kill_slack: float = KILL_SLACK_SECS,
         silence=None):
    """Run a bench phase subprocess as a WARDEN PROBE (tpu/warden.py
    LineWatch): the child's stderr is TEE'd line by line to this
    process's stderr (live heartbeats in the driver tail) while the
    last lines are buffered so a failure's JSON error stays
    attributable, and a child whose heartbeats stop for ``silence``
    seconds — a wedged runtime — is SIGKILLed immediately instead of
    at the full budget.  stdout's last line is the phase JSON.
    Returns (parsed dict, None) or (None, error string)."""
    global _CURRENT_CHILD
    from dslabs_tpu.tpu.warden import LineWatch

    # The kill slack must never push past the GLOBAL deadline — a
    # driver that enforces DSLABS_BENCH_DEADLINE_SECS externally would
    # otherwise kill US first and lose the JSON line (the rc=124
    # shape).  With too little deadline left to even start+kill a
    # child, SKIP the phase outright (best-so-far JSON beats a race).
    if _remaining() < 20:
        err = f"{label} skipped: global deadline exhausted"
        _hb(f"phase {label}: SKIPPED (deadline)")
        return None, err
    timeout = min(child_budget + kill_slack, _remaining() - 5)
    _hb(f"phase {label}: start (budget {child_budget:.0f}s, "
        f"kill at {timeout:.0f}s"
        + (f", silence kill at {silence:.0f}s" if silence else "")
        + f", deadline in {_remaining():.0f}s)")
    t0 = time.time()

    def _tee(line):
        sys.stderr.write(line)
        sys.stderr.flush()

    try:
        flight = os.path.join(_rundir(), f"{label}.flight.jsonl")
        # Live-monitor hint (ISSUE 8 satellite): any terminal can tail
        # this phase — depth/rate/skew plus the in-flight dispatch —
        # while it runs, or post-mortem after a kill.
        _hb(f"phase {label}: watch with `python -m "
            f"dslabs_tpu.tpu.telemetry watch {_rundir()}`")
        env = dict(os.environ, DSLABS_LEVEL_TIMING="1",
                   DSLABS_BENCH_FLIGHT=flight)
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + args,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
        _CURRENT_CHILD = proc
        watch = LineWatch(proc, proc.stderr, on_line=_tee)
        status, rc = watch.wait(timeout, silence=silence)
        if status == "silence":
            err = (f"{label} wedged: no heartbeat for {silence:.0f}s "
                   f"(killed at +{time.time() - t0:.0f}s; last stderr: "
                   f"{' | '.join(watch.tail[-2:])})")
            _hb(f"phase {label}: WEDGED ({err})")
            _note_wedge(label, err, watch, flight)
            return None, err
        if status == "total":
            err = (f"{label} killed at {timeout:.0f}s "
                   "(accelerator hang or compile overrun; last stderr: "
                   f"{' | '.join(watch.tail[-2:])})")
            _hb(f"phase {label}: TIMEOUT ({err})")
            _note_wedge(label, err, watch, flight)
            return None, err
        # The child's stdout is one small JSON line printed at exit, so
        # reading it after wait() cannot deadlock on a full pipe.
        stdout = proc.stdout.read()
        if rc == 0 and stdout.strip():
            out = json.loads(stdout.strip().splitlines()[-1])
            _hb(f"phase {label}: ok in {time.time() - t0:.0f}s")
            return out, None
        err = f"{label} exited rc={rc}"
        if watch.tail:
            err += f" last-stderr={watch.tail[-1]}"
        _hb(f"phase {label}: FAILED ({err})")
        _note_wedge(label, err, watch, flight)
        return None, err
    except Exception:
        err = traceback.format_exc(limit=2).strip().splitlines()[-1][:300]
        _hb(f"phase {label}: ERROR ({err})")
        _note_wedge(label, err, None, None)
        return None, err
    finally:
        _CURRENT_CHILD = None


def _load_cal_cache():
    try:
        with open(CAL_CACHE) as f:
            data = json.load(f)
        if data.get("sig") == _PROTO_SIG:
            return data["cal"]
    except Exception:
        pass
    return None


def _store_cal_cache(cal) -> None:
    try:
        with open(CAL_CACHE, "w") as f:
            json.dump({"sig": _PROTO_SIG, "cal": cal}, f)
    except Exception:
        pass


_EMITTED = False


def _ledger_path() -> str:
    return (os.environ.get("DSLABS_BENCH_LEDGER")
            or os.path.join(_rundir(), "BENCH_HISTORY.jsonl"))


def _append_ledger(result: dict) -> None:
    """Cross-run bench ledger (ISSUE 8): every run's last-line JSON —
    telemetry summaries included — appends to BENCH_HISTORY.jsonl, so
    the BENCH_r0N trajectory is a queryable artifact
    (`python -m dslabs_tpu.tpu.telemetry compare <ledger>` diffs the
    latest run against the best prior run per phase).  Never fatal —
    the ledger is an artifact, not a dependency."""
    try:
        from dslabs_tpu.tpu import telemetry as tel_mod

        path = _ledger_path()
        if tel_mod.append_ledger(
                path, dict(result, t="bench",
                           ts=round(time.time(), 1))) is not None:
            result["ledger"] = path
    except Exception:  # noqa: BLE001 — the JSON line must still print
        pass


def _emit(result: dict) -> None:
    """Print THE one JSON line (idempotent: the signal handler and the
    normal path can both reach here; only the first wins)."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    if _DIAGNOSTICS and "wedge_diagnostics" not in result:
        # Every dead phase's last heartbeat + flight-recorder spans
        # ride the error JSON (ISSUE-7 satellite; schema-pinned).
        result["wedge_diagnostics"] = _DIAGNOSTICS
    if _RUNDIR_STATE["substituted"]:
        # The run-dir fallback substitution is never silent: graders
        # reading the JSON learn where the flight logs actually are.
        result["run_dir_substituted"] = {
            "requested": _RUNDIR_REQUESTED,
            "actual": _RUNDIR_STATE["path"]}
    _append_ledger(result)
    print(json.dumps(result))
    sys.stdout.flush()


def _install_signal_emitters(result: dict) -> None:
    """Guarantee the last-line JSON even under an external kill: an
    external ``timeout``'s SIGTERM (the BENCH_r04 rc=124 shape, empty
    output) or a ^C now prints the best-so-far result — tagged with
    the signal — kills the live phase child, and exits 0."""

    def _on_signal(signum, frame):
        name = signal.Signals(signum).name
        result.setdefault(
            "error", f"killed by {name} (external timeout?) at "
                     f"+{time.time() - _T0:.0f}s")
        result["total_secs"] = round(time.time() - _T0, 1)
        child = _CURRENT_CHILD
        if child is not None:
            try:
                child.kill()
            except OSError:
                pass
        _emit(result)
        # os._exit: the handler may be interrupting arbitrary frames
        # (a child wait, a JSON dump) — unwind nothing, the line is
        # already out and exit code 0 tells the driver we reported.
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)


def _set_headline(result: dict, phase: dict, kind: str, platform: str,
                  n_dev, workload: str = "lab3-paxos") -> None:
    """Install a phase's rate as the bench's single headline number."""
    result["metric"] = (f"{workload} {kind} unique states/min "
                        f"(sharded tensor backend, {platform} x{n_dev})")
    result["value"] = round(phase["value"], 1)
    result["vs_baseline"] = round(
        phase["value"] / BASELINE_STATES_PER_MIN, 6)
    # Compile time rides SEPARATELY from the steady-state rate: with
    # the persistent compile cache warm, aot_compile_secs collapses to
    # near-zero and the headline is pure search throughput.
    for k in ("compile_secs", "aot_compile_secs"):
        if phase.get(k) is not None:
            result[k] = phase[k]
    # Robustness counters ride the headline (ISSUE 2/4): the perf
    # trajectory shows what recovery, if any, the number absorbed —
    # abandoned_threads included, so in-process watchdog degradation
    # (leaked wedged-dispatch threads) is visible in the JSON.
    for k in ("retries", "failovers", "resumed_from_depth",
              "abandoned_threads", "mesh_shrinks", "knob_retries"):
        result[k] = phase.get(k, 0)
    # Mesh-scope headline context (ISSUE 12): the width the number was
    # measured at (telemetry compare flags a silent narrow-mesh
    # fallback as a regression even at equal states/min), the
    # aggregate shard skew, and the virtual-mesh tag when the phase
    # ran on forced host-platform devices.
    for k in ("mesh_width", "skew", "virtual_cpu_mesh"):
        if phase.get(k) is not None:
            result[k] = phase[k]


def _mesh_phase(result: dict, force_cpu: bool,
                headline_ok=lambda phase: True) -> bool:
    """Run the 8-device mesh phase child (ISSUE 12) and install it;
    promotes the phase to the HEADLINE when its recovery timeline is
    clean (``mesh_shrinks == 0 && knob_retries == 0`` — a degraded run
    is recorded but never trusted as the full-width rate) and
    ``headline_ok`` agrees.  Returns True iff the headline was set."""
    if _remaining() < 60:
        result["mesh_error"] = "skipped: deadline nearly exhausted"
        return False
    budget = min(MESH_CAP_SECS, max(_remaining() - 40, 45))
    args = ["--mesh"] + (["cpu"] if force_cpu else []) + [str(budget)]
    mesh_res, mesh_err = _sub(args, budget, "mesh", kill_slack=30.0,
                              silence=PHASE_SILENCE_SECS)
    if mesh_res is None:
        result["mesh_error"] = mesh_err
        return False
    result["mesh"] = mesh_res
    _note_phase_telemetry(result, "mesh", mesh_res)
    clean = (mesh_res.get("mesh_shrinks", 0) == 0
             and mesh_res.get("knob_retries", 0) == 0
             and mesh_res.get("value", 0) > 0)
    if not (clean and headline_ok(mesh_res)):
        return False
    workload = ("lab1-clientserver c3-w4"
                if mesh_res.get("virtual_cpu_mesh") else "lab3-paxos")
    _set_headline(result, mesh_res,
                  f"strict BFS (mesh x{mesh_res['mesh_width']})",
                  mesh_res["platform"], mesh_res["mesh_width"],
                  workload=workload)
    return True


def main() -> None:
    result = {
        "metric": ("lab3-paxos strict BFS unique states/min "
                   "(sharded tensor backend)"),
        "value": 0.0, "unit": "states/min", "vs_baseline": 0.0,
        "deadline_secs": DEADLINE_SECS,
    }
    _install_signal_emitters(result)

    # ---- phase 0: pre-flight (wedge detection + platform probe).
    # Kill budget <= 120 s TOTAL (cap 90 + slack 30) and a ~60 s
    # heartbeat-silence kill: a wedged runtime dies in about a minute
    # and the 240 s CPU fallback always has deadline left (the
    # BENCH_r05 failure had the preflight eat 300 of 480 s).
    pf, pf_err = _sub(["--preflight"],
                      min(PREFLIGHT_CAP_SECS, max(_remaining() - 30, 30)),
                      "preflight",
                      kill_slack=PREFLIGHT_KILL_SLACK_SECS,
                      silence=PREFLIGHT_SILENCE_SECS)
    if pf is None:
        result["error"] = (
            "TPU runtime wedged or unreachable: pre-flight 256x256 "
            f"matmul failed ({pf_err})")
        # ---- wedged-TPU fallback: a bounded CPU bench run so the round
        # still records a REAL states/min number, tagged cpu-fallback
        # (BENCH_r04/r05 emitted 0.0 — three rounds without an official
        # perf number).
        fb, fb_err = _sub(
            ["--cpu-fallback",
             str(min(FALLBACK_CAP_SECS, max(_remaining() - 30, 60.0)))],
            min(FALLBACK_CAP_SECS, max(_remaining() - 20, 60.0)),
            "cpu-fallback", silence=PHASE_SILENCE_SECS)
        if fb is not None:
            result["backend"] = fb.get("backend", "cpu-fallback")
            result["cpu_fallback"] = fb
            _note_phase_telemetry(result, "cpu-fallback", fb)
            result["metric"] = (
                "lab1-clientserver strict BFS unique states/min "
                "(device-resident single-chip loop, cpu-fallback)")
            result["value"] = round(fb["value"], 1)
            result["vs_baseline"] = round(
                fb["value"] / BASELINE_STATES_PER_MIN, 6)
        else:
            result["error"] += f"; cpu-fallback failed: {fb_err}"
        # The 8-device mesh headline on the CPU VIRTUAL mesh (ISSUE
        # 12): a wedged TPU must not cost the round its mesh number —
        # the phase runs CPU-pinned, is tagged virtual_cpu_mesh, and
        # upgrades the headline over the single-chip fallback rate
        # when its recovery timeline is clean.
        _mesh_phase(result, force_cpu=True)
        result["total_secs"] = round(time.time() - _T0, 1)
        _emit(result)
        return
    platform, n_dev = pf["platform"], pf["n_devices"]
    on_cpu = platform == "cpu"
    result["metric"] = (f"lab3-paxos strict BFS unique states/min "
                        f"(sharded tensor backend, {platform} x{n_dev})")
    result["preflight_secs"] = pf["secs"]
    _note_phase_telemetry(result, "preflight", pf)

    if on_cpu:
        # CI / smoke shape: the 8-device virtual-mesh phase is the
        # headline (ISSUE 12), one small beam rung rides along.
        mesh_headline = _mesh_phase(result, force_cpu=True)
        beam, beam_err = _sub(
            ["--rung", "64", str(1 << 12), str(1 << 18), "30.0",
             str(FALLBACK_EV_BUDGET[0]), str(FALLBACK_EV_BUDGET[1])],
            min(BEAM_CAP_SECS, max(_remaining() - 15, 45)), "beam-cpu",
            silence=PHASE_SILENCE_SECS)
        if beam:
            if not mesh_headline:
                _set_headline(result, beam, "BFS (beam)", platform,
                              n_dev)
            result["beam"] = beam
            _note_phase_telemetry(result, "beam", beam)
        elif not mesh_headline:
            result["error"] = beam_err
        if _remaining() > 75:
            swarm, swarm_err = _sub(
                ["--swarm", str(min(60.0, _remaining() - 15))],
                min(60.0, _remaining() - 10), "swarm-cpu",
                silence=PHASE_SILENCE_SECS)
            if swarm is not None:
                result["swarm"] = swarm
                _note_phase_telemetry(result, "swarm", swarm)
        if _remaining() > 75:
            spill_res, _spill_err = _sub(
                ["--spill", str(min(90.0, _remaining() - 15))],
                min(90.0, _remaining() - 10), "spill-cpu",
                silence=PHASE_SILENCE_SECS)
            if spill_res is not None:
                result["spill"] = spill_res
                _note_phase_telemetry(result, "spill", spill_res)
        if _remaining() > 75:
            cap2, _cap2_err = _sub(
                ["--capacity2", str(min(90.0, _remaining() - 15))],
                min(90.0, _remaining() - 10), "capacity2-cpu",
                silence=PHASE_SILENCE_SECS)
            if cap2 is not None:
                result["capacity2"] = cap2
                _note_phase_telemetry(result, "capacity2", cap2)
        if _remaining() > 75:
            svc, _svc_err = _sub(
                ["--service", str(min(90.0, _remaining() - 15))],
                min(90.0, _remaining() - 10), "service-cpu",
                silence=PHASE_SILENCE_SECS)
            if svc is not None:
                result["service"] = svc
        if _remaining() > 75:
            lanes_res, _lanes_err = _sub(
                ["--lanes", str(min(120.0, _remaining() - 15))],
                min(120.0, _remaining() - 10), "lanes-cpu",
                silence=PHASE_SILENCE_SECS)
            if lanes_res is not None:
                result["lanes"] = lanes_res
        if _remaining() > 75:
            memo_res, _memo_err = _sub(
                ["--memo", str(min(120.0, _remaining() - 15))],
                min(120.0, _remaining() - 10), "memo-cpu",
                silence=PHASE_SILENCE_SECS)
            if memo_res is not None:
                result["memo"] = memo_res
        if _remaining() > 75:
            scen_res, _scen_err = _sub(
                ["--scenarios", str(min(90.0, _remaining() - 15))],
                min(90.0, _remaining() - 10), "scenarios-cpu",
                silence=PHASE_SILENCE_SECS)
            if scen_res is not None:
                result["scenarios"] = scen_res
        if _remaining() > 75:
            labs_res, _labs_err = _sub(
                ["--labs", str(min(90.0, _remaining() - 15))],
                min(90.0, _remaining() - 10), "labs-cpu",
                silence=PHASE_SILENCE_SECS)
            if labs_res is not None:
                result["labs"] = labs_res
        _emit(result)
        return

    # ---- phase 1: measured budgets (cached across runs)
    cal = _load_cal_cache()
    if cal is not None:
        _hb(f"calibration: cache hit {cal}")
        result["calibration"] = dict(cal, cached=True)
    elif _remaining() > (STRICT_CAP_SECS + CALIBRATE_CAP_SECS
                         + 2 * KILL_SLACK_SECS):
        # Cold calibration only when it cannot starve the strict phase
        # (raise DSLABS_BENCH_DEADLINE_SECS for the fully-calibrated
        # run); otherwise the round-3 measured fallback budgets hold.
        cal, cal_err = _sub(["--calibrate"], CALIBRATE_CAP_SECS,
                            "calibrate", silence=PHASE_SILENCE_SECS)
        if cal is not None:
            _store_cal_cache(cal)
            result["calibration"] = cal
        else:
            result["calibration_error"] = cal_err
    else:
        _hb("calibration: skipped (deadline reserves the window for "
            "strict; fallback ev budgets apply)")
    ev = (cal["bm"], cal["bt"]) if cal else FALLBACK_EV_BUDGET
    result["ev_budget"] = list(ev)

    # ---- phase 2: the strict drop-free headline (ONE attempt,
    # child-side budget so a slow run still lands a partial rate).  The
    # kill slack is reserved OUT of the remaining deadline so a floored
    # child (compile ate the budget, 45 s search minimum) still emits
    # its JSON before both the parent kill and the global deadline.
    strict, strict_err = None, None
    budget = min(STRICT_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 60:
        strict, strict_err = _sub(
            ["--strict", str(ev[0]), str(ev[1]), str(budget)],
            budget, "strict", silence=PHASE_SILENCE_SECS)
        if strict is not None:
            result["strict"] = strict
            _note_phase_telemetry(result, "strict", strict)
            _set_headline(result, strict, "strict BFS", platform, n_dev)
        else:
            result["strict_error"] = strict_err
    else:
        result["strict_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 3: the beam throughput rate (only with time remaining;
    # smaller fallback rungs catch an OOM on the lead config)
    beam = beam_err = None
    for chunk, f_cap, v_cap in BEAM_LADDER:
        budget = min(BEAM_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
        if budget <= 60:
            _hb("beam: skipped (deadline)")
            break
        run_secs = max(30.0, min(120.0, budget - 150.0))
        beam, beam_err = _sub(
            ["--rung", str(chunk), str(f_cap), str(v_cap),
             str(run_secs), str(ev[0]), str(ev[1])], budget,
            f"beam-{chunk}", silence=PHASE_SILENCE_SECS)
        if beam is not None:
            break
    if beam is not None:
        result["beam"] = beam
        _note_phase_telemetry(result, "beam", beam)
        if strict is None:
            _set_headline(result, beam, "BFS (beam)", platform, n_dev)
    elif strict is None:
        result["error"] = "; ".join(
            str(e) for e in (strict_err, beam_err) if e)

    # ---- phase 3.5: the 8-device mesh phase (ISSUE 12).  With >= 8
    # real accelerators it IS the headline (the paper's target
    # configuration); on a narrower box it runs the CPU virtual mesh —
    # recorded with per-device lanes + skew and compared by the
    # ledger's mesh_width guard, but never allowed to displace a real
    # accelerator headline with a virtual-mesh rate.
    _mesh_phase(result, force_cpu=False,
                headline_ok=lambda p: not p.get("virtual_cpu_mesh"))

    # ---- phase 4: the swarm explorer's deep-probe rates (walkers/sec,
    # unique-states/min, deepest depth) — the portfolio's other half.
    # Never the headline; skipped rather than raced when the deadline
    # is nearly spent.
    budget = min(SWARM_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        swarm, swarm_err = _sub(["--swarm", str(budget)], budget,
                                "swarm", silence=PHASE_SILENCE_SECS)
        if swarm is not None:
            result["swarm"] = swarm
            _note_phase_telemetry(result, "swarm", swarm)
        else:
            result["swarm_error"] = swarm_err
    else:
        result["swarm_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5: the capacity ladder (states/min at 1/8 visited
    # capacity with the host-RAM spill tier vs uncapped, exact-parity
    # flag, dropped_states == 0).  Never the headline; skipped rather
    # than raced when the deadline is nearly spent.
    budget = min(SPILL_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        spill_res, spill_err = _sub(["--spill", str(budget)], budget,
                                    "spill", silence=PHASE_SILENCE_SECS)
        if spill_res is not None:
            result["spill"] = spill_res
            _note_phase_telemetry(result, "spill", spill_res)
        else:
            result["spill_error"] = spill_err
    else:
        result["spill_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5.2: capacity round 2 (ISSUE 15) — packed vs unpacked
    # bytes_per_state + packed states/min, async spill overlap ratio,
    # symmetry quotient.  The ledger's capacity:bytes_per_state guard
    # compares it across rounds.  Never the headline; skipped rather
    # than raced when the deadline is nearly spent.
    budget = min(CAPACITY2_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        cap2, cap2_err = _sub(["--capacity2", str(budget)], budget,
                              "capacity2", silence=PHASE_SILENCE_SECS)
        if cap2 is not None:
            result["capacity2"] = cap2
            _note_phase_telemetry(result, "capacity2", cap2)
        else:
            result["capacity2_error"] = cap2_err
    else:
        result["capacity2_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5.5: the multi-tenant service drain (ISSUE 11) —
    # per-tenant throughput + the fairness index the ledger compare
    # tracks.  Never the headline; skipped rather than raced when the
    # deadline is nearly spent.
    budget = min(SERVICE_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        svc, svc_err = _sub(["--service", str(budget)], budget,
                            "service", silence=PHASE_SILENCE_SECS)
        if svc is not None:
            result["service"] = svc
        else:
            result["service_error"] = svc_err
    else:
        result["service_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5.6: batched job lanes (ISSUE 14) — aggregate
    # states/min and dispatches-per-job for a 4-lane batch vs the
    # 4-solo baseline; the ledger compare guards amortisation
    # (service:dispatches_per_job rise / lanes:occupancy drop = rc 1).
    # Never the headline; skipped rather than raced near the deadline.
    budget = min(LANES_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        lanes_res, lanes_err = _sub(["--lanes", str(budget)], budget,
                                    "lanes", silence=PHASE_SILENCE_SECS)
        if lanes_res is not None:
            result["lanes"] = lanes_res
        else:
            result["lanes_error"] = lanes_err
    else:
        result["lanes_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5.7: cross-job memoization (ISSUE 16) — cold / hit /
    # warm-start / incremental device-seconds plus the hit_rate the
    # ledger's ``memo:hit_rate`` guard tracks (drop => rc 1) and
    # ``service:device_secs_saved`` rendering.  Never the headline;
    # skipped rather than raced near the deadline.
    budget = min(MEMO_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        memo_res, memo_err = _sub(["--memo", str(budget)], budget,
                                  "memo", silence=PHASE_SILENCE_SECS)
        if memo_res is not None:
            result["memo"] = memo_res
        else:
            result["memo_error"] = memo_err
    else:
        result["memo_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5.8: fault scenarios (ISSUE 19) — states/min with the
    # partition fault lanes on vs off, the fault-event share, and the
    # zero-budget verdict_parity flag the ledger's
    # ``scenarios:verdict_parity`` guard pins (0 = rc 1 regardless of
    # threshold).  Never the headline; skipped rather than raced near
    # the deadline.
    budget = min(SCENARIOS_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        scen_res, scen_err = _sub(["--scenarios", str(budget)], budget,
                                  "scenarios",
                                  silence=PHASE_SILENCE_SECS)
        if scen_res is not None:
            result["scenarios"] = scen_res
        else:
            result["scenarios_error"] = scen_err
    else:
        result["scenarios_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 5.9: generated-labs packing (ISSUE 20) — packed
    # bytes-per-state across the ProtocolSpec-compiled lab3/lab4
    # protocols (the ``labs:bytes_per_state`` ledger guard) plus the
    # >= 2x minimum pack-ratio floor.  Never the headline; skipped
    # rather than raced near the deadline.
    budget = min(LABS_CAP_SECS, _remaining() - KILL_SLACK_SECS - 10)
    if budget > 45:
        labs_res, labs_err = _sub(["--labs", str(budget)], budget,
                                  "labs", silence=PHASE_SILENCE_SECS)
        if labs_res is not None:
            result["labs"] = labs_res
        else:
            result["labs_error"] = labs_err
    else:
        result["labs_error"] = "skipped: deadline nearly exhausted"

    # ---- phase 6: the soundness sanitizer (ISSUE 10) — findings per
    # leg + waived count off `python -m dslabs_tpu.analysis all` in a
    # CPU-pinned child (static: lowers, never compiles or dispatches).
    # `telemetry compare` flags a findings increase over the best
    # prior ledger entry as a regression, same rc-1 severity as a rate
    # drop.  Never the headline, never fatal, skipped when the
    # deadline is nearly spent.
    if _remaining() - KILL_SLACK_SECS > 30:
        try:
            from dslabs_tpu import analysis

            result["sanitizer"] = analysis.sanitizer_summary(
                timeout=max(30, min(180, int(_remaining()
                                             - KILL_SLACK_SECS))))
        except Exception as e:  # noqa: BLE001 — JSON must still land
            result["sanitizer"] = {"error": f"{type(e).__name__}: {e}"}
    else:
        result["sanitizer"] = {"error":
                               "skipped: deadline nearly exhausted"}

    result["total_secs"] = round(time.time() - _T0, 1)
    _emit(result)


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--rung":
        chunk, f_cap, v_cap = map(int, sys.argv[2:5])
        ev = ((int(sys.argv[6]), int(sys.argv[7]))
              if len(sys.argv) > 7 else FALLBACK_EV_BUDGET)
        print(json.dumps(_run_rung(chunk, f_cap, v_cap,
                                   float(sys.argv[5]), ev)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--strict":
        ev = (int(sys.argv[2]), int(sys.argv[3]))
        budget = (float(sys.argv[4]) if len(sys.argv) > 4
                  else STRICT_CAP_SECS)
        print(json.dumps(_run_strict(ev, budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--swarm":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else SWARM_CAP_SECS)
        print(json.dumps(_run_swarm(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--spill":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else SPILL_CAP_SECS)
        print(json.dumps(_run_spill(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--capacity2":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else CAPACITY2_CAP_SECS)
        print(json.dumps(_run_capacity2(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--service":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else SERVICE_CAP_SECS)
        print(json.dumps(_run_service(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--lanes":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else LANES_CAP_SECS)
        print(json.dumps(_run_lanes(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--memo":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else MEMO_CAP_SECS)
        print(json.dumps(_run_memo(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--scenarios":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else SCENARIOS_CAP_SECS)
        print(json.dumps(_run_scenarios(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--labs":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else LABS_CAP_SECS)
        print(json.dumps(_run_labs(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--mesh":
        # The 8-wide mesh needs 8 devices SOMEWHERE: force the host
        # platform's virtual device count before jax loads so
        # make_mesh(8) can fall back to the CPU virtual mesh on narrow
        # boxes.  A leading "cpu" arg pins the whole child to the CPU
        # backend (the wedged-TPU branch must never touch the runtime).
        _args = sys.argv[2:]
        if _args and _args[0] == "cpu":
            os.environ["DSLABS_FORCE_CPU"] = "1"
            _args = _args[1:]
        _xf = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _xf:
            os.environ["XLA_FLAGS"] = (
                _xf + " --xla_force_host_platform_device_count="
                + os.environ.get("DSLABS_MESH_WIDTH", "8")).strip()
        print(json.dumps(_run_mesh(
            float(_args[0]) if _args else MESH_CAP_SECS)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--calibrate":
        print(json.dumps(_calibrate()))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--cpu-fallback":
        budget = (float(sys.argv[2]) if len(sys.argv) > 2
                  else FALLBACK_CAP_SECS)
        print(json.dumps(_cpu_fallback(budget)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--preflight":
        print(json.dumps(_preflight()))
        sys.exit(0)
    try:
        main()
    except BaseException:
        # The last line of defense for "bench never reports nothing":
        # ANY escape from main (SystemExit from a signal handler
        # already emitted; everything else lands here) still prints a
        # tagged, parsable JSON line and exits 0.
        tb = traceback.format_exc(limit=3)
        _emit({
            "metric": "lab3-paxos strict BFS unique states/min "
                      "(tensor backend)",
            "value": 0.0, "unit": "states/min", "vs_baseline": 0.0,
            "error": tb.strip().splitlines()[-1][:300],
            "total_secs": round(time.time() - _T0, 1),
        })
        sys.exit(0)
