"""Benchmark: lab3 multi-Paxos BFS unique-states/minute on the TPU tensor
backend (BASELINE.md north star: >= 1e8 unique lab3-paxos states/min on a
v5e-8; this runs on whatever single chip the driver provides).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

BASELINE_STATES_PER_MIN = 1e8


def main() -> None:
    import jax

    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.paxos import make_paxos_protocol

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    # Two clients widen the space enough to sustain large frontiers.
    protocol = make_paxos_protocol(n=3, n_clients=2, w=1, max_slots=3,
                                   net_cap=64, timer_cap=6)
    chunk = 2048 if on_tpu else 256
    search = TensorSearch(protocol, frontier_cap=1 << 22, chunk=chunk,
                          max_depth=1)
    search.run()  # warm-up: compiles the level program

    search.max_depth = 64
    search.max_secs = 120.0 if on_tpu else 60.0
    t0 = time.time()
    outcome = search.run()
    elapsed = max(time.time() - t0, 1e-9)
    states_per_min = outcome.unique_states / elapsed * 60.0
    print(json.dumps({
        "metric": "lab3-paxos BFS unique states/min (tensor backend, "
                  f"{'tpu' if on_tpu else jax.devices()[0].platform})",
        "value": round(states_per_min, 1),
        "unit": "states/min",
        "vs_baseline": round(states_per_min / BASELINE_STATES_PER_MIN, 6),
    }))


if __name__ == "__main__":
    sys.exit(main())
