"""Interactive branch-exploring debugger (DebuggerWindow.java:89 +
EventTreeState.java:47-209 capability, web-native).

A tiny stdlib HTTP server holds an execution TREE over live
:class:`SearchState` objects: the client shows the current state with
field-level diff highlighting against its parent, lists the state's
PENDING events (deliverable messages + timers — exactly
``SearchState.events()``, so duplicate deliveries are offered the same
way ``EventTreeState`` detects "sends delivered messages"), and a click
delivers one, creating (or revisiting — steps are cached per
(node, event)) a child branch.  Navigation walks the whole explored
tree, not a fixed linear trace.

Entry points:
  * ``run_tests.py --debugger <lab> <vizconfig args>`` — from a lab's
    initial state (VizClient.java:39-102).
  * ``run_tests.py --visualize-trace <file>`` — the saved trace is
    replayed into an initial PATH through the tree; the user can step
    along it or deviate anywhere (SavedTraceViz.java:31-55 + branch
    exploration).
"""

from __future__ import annotations

import json
import threading
import webbrowser
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from dslabs_tpu.viz.server import state_dump

__all__ = ["EventTree", "serve_debugger"]


class _TreeNode:
    __slots__ = ("id", "state", "parent", "event_repr", "children", "depth")

    def __init__(self, id_, state, parent, event_repr, depth):
        self.id = id_
        self.state = state
        self.parent = parent              # parent node id or None
        self.event_repr = event_repr      # repr of the event that made us
        self.children: Dict[int, int] = {}  # pending-event idx -> node id
        self.depth = depth


class EventTree:
    """Explored-execution tree over SearchStates (EventTreeState
    analog): step caching, path-from-initial, pending-event listing."""

    def __init__(self, initial_state, settings=None):
        self.settings = settings
        self.nodes: List[_TreeNode] = [
            _TreeNode(0, initial_state, None, "(initial state)", 0)]
        # ThreadingHTTPServer handles requests on separate threads; node
        # creation must be serialised or two concurrent /step calls
        # could mint the same node id.
        self._lock = threading.Lock()

    def pending(self, node_id: int) -> List:
        return self.nodes[node_id].state.events(self.settings)

    def step(self, node_id: int, event_idx: int) -> Optional[int]:
        """Deliver pending event ``event_idx`` of node ``node_id``;
        returns the child node id (cached if already explored) or None
        if the event is no longer deliverable."""
        with self._lock:
            node = self.nodes[node_id]
            if event_idx in node.children:
                return node.children[event_idx]
            events = self.pending(node_id)
            if not 0 <= event_idx < len(events):
                return None
            event = events[event_idx]
            child_state = node.state.step_event(event, self.settings,
                                                skip_checks=True)
            if child_state is None:
                return None
            child = _TreeNode(len(self.nodes), child_state, node_id,
                              repr(event), node.depth + 1)
            self.nodes.append(child)
            node.children[event_idx] = child.id
            return child.id

    def preload_path(self, events) -> List[int]:
        """Replay a recorded event list from the root into a path of
        tree nodes (the --visualize-trace entry)."""
        path = [0]
        node_id = 0
        for event in events:
            pend = self.pending(node_id)
            idx = next((i for i, e in enumerate(pend) if e == event), None)
            if idx is None:
                break
            nxt = self.step(node_id, idx)
            if nxt is None:
                break
            node_id = nxt
            path.append(node_id)
        return path

    # ------------------------------------------------------------- JSON

    def tree_json(self) -> dict:
        """The whole explored tree (StateTreeCanvas.java capability):
        one record per node, DFS-ordered so the client can lay out
        subtrees contiguously."""
        order: List[int] = []
        with self._lock:
            # Iterative DFS: preloaded traces can be thousands of events
            # deep — recursion would overflow inside the HTTP handler.
            stack = [0]
            while stack:
                nid = stack.pop()
                order.append(nid)
                kids = [cid for _, cid in
                        sorted(self.nodes[nid].children.items())]
                stack.extend(reversed(kids))
            return {"nodes": [{
                "id": nid,
                "parent": self.nodes[nid].parent,
                "depth": self.nodes[nid].depth,
                "event": self.nodes[nid].event_repr[:80],
            } for nid in order]}

    def node_json(self, node_id: int) -> dict:
        node = self.nodes[node_id]
        parent = (self.nodes[node.parent] if node.parent is not None
                  else None)
        pend = self.pending(node_id)
        # Ancestor path root-first — the trace breadcrumb.
        path = []
        cur = node
        while cur is not None:
            path.append({"id": cur.id, "event": cur.event_repr})
            cur = self.nodes[cur.parent] if cur.parent is not None else None
        path.reverse()
        return {
            "id": node.id,
            "depth": node.depth,
            "event": node.event_repr,
            "parent": node.parent,
            "state": state_dump(node.state),
            "parent_state": state_dump(parent.state) if parent else None,
            "pending": [{"idx": i, "repr": repr(e),
                         "kind": type(e).__name__,
                         "child": node.children.get(i)}
                        for i, e in enumerate(pend)],
            "path": path,
            "children": node.children,
            "n_nodes": len(self.nodes),
        }


_APP = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>dslabs debugger</title>
<style>
 body { font-family: ui-monospace, Menlo, monospace; margin: 0;
        background: #11151a; color: #d6dde6; }
 header { padding: 10px 16px; background: #1a212b; display: flex;
          gap: 14px; align-items: center; flex-wrap: wrap; }
 header b { color: #7fd1b9; }
 button { background: #2b3a4d; color: #d6dde6; border: 0;
          padding: 4px 10px; border-radius: 4px; cursor: pointer;
          font: inherit; font-size: 12px; }
 button:hover { background: #3b4f68; }
 button.visited { background: #24503d; }
 #crumb { padding: 6px 16px; color: #e8c268; font-size: 12px;
          white-space: pre-wrap; }
 #crumb a { color: #8ab4f8; cursor: pointer; text-decoration: none; }
 .cols { display: flex; gap: 12px; padding: 0 16px 16px;
         align-items: flex-start; }
 .events { background: #1a212b; border-radius: 6px; padding: 10px;
           width: 420px; flex-shrink: 0; }
 .events h3, .panel h3 { margin: 0 0 6px; color: #8ab4f8;
                         font-size: 14px; }
 .ev { display: flex; gap: 6px; margin: 3px 0; align-items: baseline; }
 .ev .r { font-size: 12px; word-break: break-all; }
 .statecols { display: flex; flex-wrap: wrap; gap: 12px; flex: 1; }
 .panel { background: #1a212b; border-radius: 6px; padding: 10px 12px;
          min-width: 260px; max-width: 520px; flex: 1; }
 .field { padding: 1px 0; font-size: 12.5px; white-space: pre-wrap;
          word-break: break-all; }
 .field .k { color: #9aa7b5 }
 .changed { background: #3d3118; border-radius: 3px; }
 .small { font-size: 12px; color: #9aa7b5 }
 #treewrap { background: #1a212b; border-radius: 6px; margin: 0 16px 12px;
             padding: 8px; overflow: auto; max-height: 260px; }
 #treewrap h3 { margin: 0 0 4px; color: #8ab4f8; font-size: 14px; }
 #tree circle { cursor: pointer; fill: #2b3a4d; stroke: #56718f; }
 #tree circle:hover { fill: #3b4f68; }
 #tree circle.onpath { fill: #24503d; stroke: #7fd1b9; }
 #tree circle.cur { fill: #e8c268; stroke: #e8c268; }
 #tree line { stroke: #31404f; stroke-width: 1.2; }
 #tree line.onpath { stroke: #7fd1b9; stroke-width: 2; }
 #tree text { fill: #9aa7b5; font-size: 9px; pointer-events: none; }
</style></head><body>
<header>
 <b>dslabs debugger</b>
 <button id="up">&#8593; parent</button>
 <span id="pos" class="small"></span>
 <span id="count" class="small"></span>
</header>
<div id="crumb"></div>
<div id="treewrap"><h3>explored tree (click a node to jump)</h3>
 <svg id="tree" width="100" height="100"></svg></div>
<div class="cols">
 <div class="events"><h3>pending events (click to deliver)</h3>
   <div id="pending"></div></div>
 <div class="statecols" id="nodes"></div>
</div>
<script>
let cur = 0;
function esc(s) { return String(s).replace(/&/g, "&amp;")
  .replace(/</g, "&lt;").replace(/>/g, "&gt;"); }
function fields(curF, prevF) {
  let out = "";
  for (const k of Object.keys(curF)) {
    const changed = prevF && prevF[k] !== curF[k];
    out += `<div class="field ${changed ? "changed" : ""}">` +
           `<span class="k">${esc(k)}</span> = ${esc(curF[k])}</div>`;
  }
  if (prevF) for (const k of Object.keys(prevF))
    if (!(k in curF))
      out += `<div class="field changed"><span class="k">${esc(k)}</span>` +
             ` (deleted)</div>`;
  return out;
}
let treeCache = null, treeCacheN = -1;
async function drawTree(pathIds, nNodes) {
  if (treeCacheN !== nNodes) {
    const r = await fetch(`/tree`);
    treeCache = await r.json();
    treeCacheN = nNodes;
  }
  const d = treeCache;
  const dx = 46, dy = 26, r0 = 7;
  const pos = {};                       // id -> [x, y]
  let row = 0;
  // DFS order from the server: a node's y is its subtree's first free
  // row; depth sets x — the classic left-to-right layered tree.
  const seenDepth = {};
  for (const n of d.nodes) {
    if (n.parent === null) { pos[n.id] = [0, row]; continue; }
    // place on parent's row if free, else next free row
    const py = pos[n.parent][1];
    let y = py;
    while (seenDepth[n.depth] !== undefined && y <= seenDepth[n.depth])
      y = seenDepth[n.depth] + 1;
    seenDepth[n.depth] = y;
    pos[n.id] = [n.depth, y];
    row = Math.max(row, y);
  }
  const onPath = new Set(pathIds);
  let maxX = 0, maxY = 0;
  let edges = "", nodes = "";
  for (const n of d.nodes) {
    const [x, y] = pos[n.id];
    maxX = Math.max(maxX, x); maxY = Math.max(maxY, y);
    if (n.parent !== null) {
      const [px, py] = pos[n.parent];
      const cls = onPath.has(n.id) && onPath.has(n.parent) ? "onpath" : "";
      edges += `<line class="${cls}" x1="${px*dx+16}" y1="${py*dy+16}" ` +
               `x2="${x*dx+16}" y2="${y*dy+16}"><title></title></line>`;
    }
    const cls = n.id === cur ? "cur" : (onPath.has(n.id) ? "onpath" : "");
    nodes += `<circle class="${cls}" cx="${x*dx+16}" cy="${y*dy+16}" ` +
             `r="${r0}" onclick="load(${n.id})">` +
             `<title>#${n.id} d${n.depth}: ${esc(n.event)}</title></circle>` +
             `<text x="${x*dx+12}" y="${y*dy+35}">${n.id}</text>`;
  }
  const svg = document.getElementById("tree");
  svg.setAttribute("width", maxX*dx+40);
  svg.setAttribute("height", maxY*dy+44);
  svg.innerHTML = edges + nodes;
}
async function load(id) {
  const r = await fetch(`/node/${id}`);
  const d = await r.json();
  cur = d.id;
  drawTree(d.path.map(p => p.id), d.n_nodes);
  document.getElementById("pos").textContent =
    `node ${d.id} · depth ${d.depth}`;
  document.getElementById("count").textContent =
    `· ${d.n_nodes} states explored`;
  document.getElementById("crumb").innerHTML = d.path.map(
    (p, i) => `<a onclick="load(${p.id})">[${i}]</a> ${esc(p.event)}`
  ).join("\\n");
  let ph = "";
  for (const e of d.pending) {
    const cls = e.child !== null && e.child !== undefined ? "visited" : "";
    ph += `<div class="ev"><button class="${cls}" ` +
          `onclick="deliver(${e.idx})">deliver</button>` +
          `<span class="r">${esc(e.repr)}</span></div>`;
  }
  document.getElementById("pending").innerHTML =
    ph || "<span class='small'>(no deliverable events)</span>";
  let nh = "";
  const prev = d.parent_state;
  for (const a of Object.keys(d.state.nodes)) {
    nh += `<div class="panel"><h3>${esc(a)}</h3>` +
          fields(d.state.nodes[a], prev ? prev.nodes[a] : null) + `</div>`;
  }
  const pnet = prev ? new Set(prev.network) : new Set();
  nh += `<div class="panel"><h3>network (message set)</h3>` +
        d.state.network.map(m =>
          `<div class="field ${pnet.has(m) ? "" : "changed"}">` +
          `${esc(m)}</div>`).join("") + `</div>`;
  let th = "";
  for (const a of Object.keys(d.state.timers))
    for (const t of d.state.timers[a])
      th += `<div class="field">${esc(t)}</div>`;
  nh += `<div class="panel"><h3>pending timers</h3>${th}</div>`;
  document.getElementById("nodes").innerHTML = nh;
  document.getElementById("up").disabled = d.parent === null;
  document.getElementById("up").onclick =
    () => { if (d.parent !== null) load(d.parent); };
}
async function deliver(idx) {
  const r = await fetch(`/step`, {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({id: cur, event: idx})});
  const d = await r.json();
  if (d.child !== null) load(d.child);
}
load(__START__);
</script></body></html>
"""


def serve_debugger(initial_state, settings=None, port: int = 0,
                   preload_events=None, open_browser: bool = True,
                   block: bool = True):
    """Serve the branch-exploring debugger on localhost; returns the
    (server, tree) pair (server already running on a daemon thread when
    ``block`` is False — used by the tests)."""
    tree = EventTree(initial_state, settings)
    start = 0
    if preload_events:
        path = tree.preload_path(preload_events)
        start = path[-1]

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code=200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/", "/index.html"):
                body = _APP.replace("__START__", str(start)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/tree":
                self._json(tree.tree_json())
            elif self.path.startswith("/node/"):
                try:
                    node_id = int(self.path[len("/node/"):])
                    self._json(tree.node_json(node_id))
                except (ValueError, IndexError):
                    self._json({"error": "bad node id"}, 404)
            else:
                self._json({"error": "not found"}, 404)

        def do_POST(self):
            if self.path != "/step":
                self._json({"error": "not found"}, 404)
                return
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n) or b"{}")
            child = tree.step(int(req.get("id", 0)),
                              int(req.get("event", -1)))
            self._json({"child": child})

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    url = f"http://127.0.0.1:{server.server_address[1]}/"
    print(f"dslabs debugger at {url} (ctrl-c to stop)")
    if open_browser:
        try:
            webbrowser.open(url)
        except Exception:
            pass
    if block:
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            server.server_close()
    else:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
    return server, tree
