"""Trace -> HTML rendering + the @viz_ignore field annotation.

The reference renders live object trees with reflection and diff
highlighting (JTrees.java:146-268: NEW/CHANGED/DELETED) and hides fields
annotated @VizIgnore (VizIgnore.java:30-37).  Here each state along the
causal trace is dumped once to JSON (field name -> repr, honouring
``viz_ignore``) and a static page does navigation + diffing client-side —
no server process, no Swing: ``serve_trace`` writes the page next to the
trace and prints its path."""

from __future__ import annotations

import html
import json
import os
from typing import List, Optional

__all__ = ["viz_ignore", "render_trace_html", "serve_trace", "state_dump"]


def viz_ignore(*field_names: str):
    """Class decorator marking fields hidden from the debugger
    (@VizIgnore analog): ``@viz_ignore("cache", "_tmp")``."""

    def deco(cls):
        existing = getattr(cls, "__viz_ignore__", ())
        cls.__viz_ignore__ = tuple(existing) + tuple(field_names)
        return cls

    return deco


def _node_fields(node) -> dict:
    ignored = set(getattr(type(node), "__viz_ignore__", ()))
    out = {}
    for k, v in vars(node).items():
        if k.startswith("_") or k in ignored:
            continue
        out[k] = repr(v)
    return out


def state_dump(state) -> dict:
    """One search state -> JSON-able dict (nodes, network, timers)."""
    nodes = {}
    for a in state.addresses():
        nodes[str(a)] = _node_fields(state.node(a))
    net = sorted(repr(m) for m in state.network())
    timers = {}
    for a in state.addresses():
        tq = state.timers(a)
        if tq is not None:
            rows = [repr(t) for t in tq]
            if rows:
                timers[str(a)] = rows
    return {"nodes": nodes, "network": net, "timers": timers}


def trace_dump(trace) -> List[dict]:
    """SerializableTrace -> per-step dumps: [{event, state}]."""
    state = trace.initial_state()
    steps = [{"event": "(initial state)", "state": state_dump(state)}]
    for event in trace.history:
        nxt = state.step_event(event, None, skip_checks=True)
        if nxt is None:
            steps.append({"event": f"UNDELIVERABLE: {event!r}",
                          "state": state_dump(state)})
            break
        state = nxt
        steps.append({"event": repr(event), "state": state_dump(state)})
    return steps


_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>dslabs trace: __TITLE__</title>
<style>
 body { font-family: ui-monospace, Menlo, monospace; margin: 0;
        background: #11151a; color: #d6dde6; }
 header { padding: 10px 16px; background: #1a212b;
          display: flex; gap: 14px; align-items: center; }
 header b { color: #7fd1b9; }
 button { background: #2b3a4d; color: #d6dde6; border: 0;
          padding: 6px 14px; border-radius: 4px; cursor: pointer; }
 button:disabled { opacity: .4 }
 #event { padding: 8px 16px; color: #e8c268; white-space: pre-wrap; }
 main { display: flex; flex-wrap: wrap; gap: 12px; padding: 0 16px 16px; }
 .panel { background: #1a212b; border-radius: 6px; padding: 10px 12px;
          min-width: 280px; max-width: 520px; flex: 1; }
 .panel h3 { margin: 0 0 6px; color: #8ab4f8; font-size: 14px; }
 .field { padding: 1px 0; font-size: 12.5px; white-space: pre-wrap;
          word-break: break-all; }
 .field .k { color: #9aa7b5 }
 .changed { background: #3d3118; border-radius: 3px; }
 .lists { width: 100%; display: flex; gap: 12px; }
 .small { font-size: 12px; color: #9aa7b5 }
</style></head><body>
<header>
 <b>dslabs trace viewer</b>
 <button id="prev">&#8592; prev</button>
 <span id="pos"></span>
 <button id="next">next &#8594;</button>
 <span class="small">__TITLE__</span>
</header>
<div id="event"></div>
<main id="nodes"></main>
<main class="lists">
 <div class="panel" style="flex:2"><h3>network (message set)</h3>
   <div id="net"></div></div>
 <div class="panel"><h3>pending timers</h3><div id="timers"></div></div>
</main>
<script>
const STEPS = __STEPS__;
let i = 0;
function fields(cur, prev) {
  let out = "";
  const keys = Object.keys(cur);
  for (const k of keys) {
    const changed = prev && prev[k] !== cur[k];
    out += `<div class="field ${changed ? "changed" : ""}">` +
           `<span class="k">${esc(k)}</span> = ${esc(cur[k])}</div>`;
  }
  if (prev) for (const k of Object.keys(prev))
    if (!(k in cur))
      out += `<div class="field changed"><span class="k">${esc(k)}</span>` +
             ` (deleted)</div>`;
  return out;
}
function esc(s) { return String(s).replace(/&/g, "&amp;")
  .replace(/</g, "&lt;").replace(/>/g, "&gt;"); }
function render() {
  const s = STEPS[i], p = i > 0 ? STEPS[i - 1] : null;
  document.getElementById("pos").textContent = `step ${i}/${STEPS.length - 1}`;
  document.getElementById("event").textContent = s.event;
  let nh = "";
  for (const a of Object.keys(s.state.nodes)) {
    nh += `<div class="panel"><h3>${esc(a)}</h3>` +
          fields(s.state.nodes[a], p ? p.state.nodes[a] : null) + `</div>`;
  }
  document.getElementById("nodes").innerHTML = nh;
  const pnet = p ? new Set(p.state.network) : new Set();
  document.getElementById("net").innerHTML = s.state.network.map(
    m => `<div class="field ${pnet.has(m) ? "" : "changed"}">${esc(m)}</div>`
  ).join("");
  let th = "";
  for (const a of Object.keys(s.state.timers)) {
    for (const t of s.state.timers[a])
      th += `<div class="field">${esc(t)}</div>`;
  }
  document.getElementById("timers").innerHTML = th;
  document.getElementById("prev").disabled = i === 0;
  document.getElementById("next").disabled = i === STEPS.length - 1;
}
document.getElementById("prev").onclick = () => { if (i > 0) { i--; render(); } };
document.getElementById("next").onclick = () => { if (i < STEPS.length - 1) { i++; render(); } };
document.addEventListener("keydown", e => {
  if (e.key === "ArrowLeft") document.getElementById("prev").click();
  if (e.key === "ArrowRight") document.getElementById("next").click();
});
render();
</script></body></html>
"""


def render_trace_html(trace) -> str:
    steps = trace_dump(trace)
    title = html.escape(repr(trace))
    return (_PAGE.replace("__TITLE__", title)
            .replace("__STEPS__", json.dumps(steps).replace("</", "<\\/")))


def serve_trace(path: str, out_path: Optional[str] = None) -> int:
    """Render a saved trace to HTML next to it (SavedTraceViz.main
    analog, SavedTraceViz.java:31-55).  Returns a process exit code."""
    from dslabs_tpu.search.trace import SerializableTrace

    trace = SerializableTrace.load(path)
    if trace is None:
        print(f"Could not load trace {path}")
        return 1
    out_path = out_path or path + ".html"
    with open(out_path, "w") as f:
        f.write(render_trace_html(trace))
    print(f"Trace rendered to {out_path} — open it in a browser "
          f"({len(trace.history)} events)")
    return 0
