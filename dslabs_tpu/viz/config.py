"""Per-lab debugger configurations — VizConfig re-design
(visualization/VizConfig.java:46-131): each lab registers a builder that
parses ``numServers numClients workload...`` CLI-style arguments into an
initial SearchState, so `run_tests.py --debugger -l LAB args...` (and the
trace viewer's synthetic-trace mode) can start from a fresh system."""

from __future__ import annotations

from typing import Callable, Dict, List

__all__ = ["VizConfig", "register_viz_config", "viz_configs"]

VizConfig = Callable[[List[str]], object]   # args -> SearchState

_CONFIGS: Dict[str, VizConfig] = {}


def register_viz_config(lab: str):
    def deco(fn: VizConfig) -> VizConfig:
        _CONFIGS[str(lab)] = fn
        return fn

    return deco


def viz_configs() -> Dict[str, VizConfig]:
    _ensure_builtin()
    return dict(_CONFIGS)


def _ensure_builtin() -> None:
    if "0" in _CONFIGS:
        return

    @register_viz_config("0")
    def lab0(args: List[str]):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingClient,
                                                       PingServer, Pong)
        from dslabs_tpu.search.search_state import SearchState
        from dslabs_tpu.testing.generator import NodeGenerator
        from dslabs_tpu.testing.workload import Workload

        n_clients = int(args[1]) if len(args) > 1 else 1
        cmds = args[2].split(",") if len(args) > 2 else ["hello"]
        server = LocalAddress("pingserver")
        gen = NodeGenerator(
            server_supplier=lambda a: PingServer(a),
            client_supplier=lambda a: PingClient(a, server),
            workload_supplier=lambda a: Workload(
                command_strings=list(cmds), result_strings=list(cmds),
                parser=lambda c, r: (Ping(c),
                                     Pong(r) if r is not None else None)))
        state = SearchState(gen)
        state.add_server(server)
        for i in range(1, n_clients + 1):
            state.add_client_worker(LocalAddress(f"client{i}"))
        return state

    @register_viz_config("1")
    def lab1(args: List[str]):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.clientserver import (SimpleClient,
                                                               SimpleServer)
        from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
        from dslabs_tpu.labs.clientserver.kvstore import KVStore
        from dslabs_tpu.search.search_state import SearchState
        from dslabs_tpu.testing.generator import NodeGenerator

        n_clients = int(args[1]) if len(args) > 1 else 1
        cmds = (args[2].split(",") if len(args) > 2
                else ["PUT:foo:bar", "GET:foo"])
        server = LocalAddress("server")
        gen = NodeGenerator(
            server_supplier=lambda a: SimpleServer(a, KVStore()),
            client_supplier=lambda a: SimpleClient(a, server),
            workload_supplier=lambda a: kv_workload(list(cmds)))
        state = SearchState(gen)
        state.add_server(server)
        for i in range(1, n_clients + 1):
            state.add_client_worker(LocalAddress(f"client{i}"))
        return state

    @register_viz_config("3")
    def lab3(args: List[str]):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.kv_workload import kv_workload
        from dslabs_tpu.labs.clientserver.kvstore import KVStore
        from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
        from dslabs_tpu.search.search_state import SearchState
        from dslabs_tpu.testing.generator import NodeGenerator

        n_servers = int(args[0]) if args else 3
        n_clients = int(args[1]) if len(args) > 1 else 1
        cmds = (args[2].split(",") if len(args) > 2
                else ["PUT:foo:bar", "GET:foo"])
        servers = tuple(LocalAddress(f"server{i}")
                        for i in range(1, n_servers + 1))
        gen = NodeGenerator(
            server_supplier=lambda a: PaxosServer(a, servers, KVStore()),
            client_supplier=lambda a: PaxosClient(a, servers),
            workload_supplier=lambda a: kv_workload(list(cmds)))
        state = SearchState(gen)
        for a in servers:
            state.add_server(a)
        for i in range(1, n_clients + 1):
            state.add_client_worker(LocalAddress(f"client{i}"))
        return state
