"""Visual debugger — web-based trace viewer.

Re-design of the reference's Swing debugger (visualization/
DebuggerWindow.java:89, JTrees.java:89-1052, VizConfig.java:46-131) as a
self-contained static HTML page: per-node state panels with field-level
diff highlighting between consecutive states, the delivered-event list
with step navigation, and the pending message/timer views.  Consumes the
same SerializableTrace format the harness saves (`-s`) and the CLI opens
(`run_tests.py --visualize-trace FILE`)."""

from dslabs_tpu.viz.config import VizConfig, register_viz_config, viz_configs
from dslabs_tpu.viz.server import render_trace_html, serve_trace, viz_ignore

__all__ = ["render_trace_html", "serve_trace", "viz_ignore", "VizConfig",
           "register_viz_config", "viz_configs"]
