"""``python -m dslabs_tpu.analysis`` — the soundness-sanitizer CLI
(ISSUE 10).  The env pinning must happen BEFORE anything imports jax:
the audit is static (trace + lower, never compile/dispatch), so it
always runs on a virtual CPU mesh and leaves the accelerator alone —
the same discipline as tests/conftest.py."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from dslabs_tpu.analysis import main  # noqa: E402

sys.exit(main())
