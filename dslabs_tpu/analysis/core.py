"""Shared sanitizer plumbing: the finding record, the rule catalog,
and the waiver file (ISSUE 10).

A finding is one rule violation at one location.  Conformance findings
(C1-C4) locate as ``<repo-relative-path>::<qualname>``; jaxpr-audit
findings (J0-J5) locate as ``<engine-class>::<dispatch-tag>``.  Either
way ``Finding.target`` is the string waiver patterns match against.

Waiver file (default ``<repo root>/.sanitizer-waivers``), one waiver
per line::

    # comment
    <CODE> <target-glob> <one-line justification>

e.g. ::

    C2 dslabs_tpu/labs/paxos/paxos.py::*  tie-break seeded by harness

``<CODE>`` is a rule code or ``*``; ``<target-glob>`` is an
``fnmatch`` pattern over ``Finding.target``.  A waived finding still
prints (marked ``waived``) but does not fail the CLI / the compile
gate / the bench sanitizer block — the waiver IS the documentation of
the justified exception (docs/analysis.md).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
from typing import List, Optional, Sequence

__all__ = ["Finding", "Waiver", "RULES", "load_waivers", "apply_waivers",
           "render_findings", "default_waiver_path", "repo_root"]

# The rule catalog — docs/analysis.md mirrors this table.
RULES = {
    "C1": "handler purity: mutation of a received message/timer "
          "payload, or aliasing mutable node state into a send",
    "C2": "nondeterminism: random/time/id()/unordered set iteration "
          "inside a handler (breaks replay, minimization, and "
          "fingerprint determinism)",
    "C3": "dedup soundness: public node-state field that defeats "
          "structural freeze/hash (utils.structural.sfreeze)",
    "C4": "spec hygiene: declared message/timer with no handler, "
          "put/get of undeclared fields, handler for unknown "
          "kind/message",
    "C5": "symmetry hygiene: a handler on a kind inside a declared "
          "symmetry group branches on the raw node id (node_index() "
          "compared against a constant) — breaks member "
          "interchangeability, so the canonicalize pass would merge "
          "states with DIFFERENT behavior",
    "C6": "fault-model opacity: a handler reads or branches on fault "
          "controller internals (the '$fault' kind or its "
          "pcut/eras/crashes/drops/dups/down_* lanes) — protocols "
          "must observe faults only through message loss and timer "
          "silence, or the scenario stops modeling a real network",
    "J0": "site-registry coverage: dispatch site missing from "
          "telemetry.DISPATCH_SITES, or its program failed to lower",
    "J1": "host callback inside a lowered device program",
    "J2": "float64 upcast in a lowered device program",
    "J3": "donation audit: large carry declared donated but the "
          "lowering kept no input/output aliasing",
    "J4": "unexpected cross-device collective in a single-device "
          "program",
    "J5": "retrace hazard: rebuilding the program lowers to different "
          "HLO (compile-cache key churn after AOT warm-up)",
}


@dataclasses.dataclass
class Finding:
    code: str                  # rule code, RULES key
    leg: str                   # "conformance" | "jaxpr"
    path: str                  # repo-relative file, or engine class
    obj: str                   # qualname, or dispatch tag
    message: str
    line: int = 0
    waived: bool = False
    waiver: str = ""           # justification of the matching waiver

    @property
    def target(self) -> str:
        return f"{self.path}::{self.obj}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        tag = f"[{self.code}]"
        w = f"  (waived: {self.waiver})" if self.waived else ""
        return f"{tag} {loc} {self.obj}: {self.message}{w}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Waiver:
    code: str                  # rule code or "*"
    pattern: str               # fnmatch glob over Finding.target
    reason: str

    def matches(self, f: Finding) -> bool:
        return (self.code in ("*", f.code)
                and fnmatch.fnmatch(f.target, self.pattern))


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_waiver_path() -> str:
    return os.environ.get("DSLABS_SANITIZE_WAIVERS") or os.path.join(
        repo_root(), ".sanitizer-waivers")


def load_waivers(path: Optional[str] = None) -> List[Waiver]:
    """Parse the waiver file; a missing file is an empty waiver set, a
    malformed LINE is a loud ValueError (a silently-dropped waiver
    would flip the CLI red with no hint why)."""
    path = path or default_waiver_path()
    out: List[Waiver] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for n, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 2)
            if len(parts) < 3:
                raise ValueError(
                    f"{path}:{n}: waiver needs '<CODE> <target-glob> "
                    f"<justification>', got {line!r}")
            code, pattern, reason = parts
            if code != "*" and code not in RULES:
                raise ValueError(
                    f"{path}:{n}: unknown rule code {code!r} "
                    f"(known: {sorted(RULES)})")
            out.append(Waiver(code, pattern, reason))
    return out


def apply_waivers(findings: Sequence[Finding],
                  waivers: Sequence[Waiver]) -> List[Finding]:
    for f in findings:
        for w in waivers:
            if w.matches(f):
                f.waived = True
                f.waiver = w.reason
                break
    return list(findings)


def render_findings(findings: Sequence[Finding],
                    header: str = "sanitizer") -> str:
    live = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]
    out = [f"== {header}: {len(live)} finding(s)"
           + (f", {len(waived)} waived" if waived else "") + " =="]
    for f in findings:
        out.append(f.render())
    if not findings:
        out.append("clean: no findings")
    return "\n".join(out)
