"""Soundness sanitizer (ISSUE 10): static correctness tooling with two
legs behind one CLI —

* **Leg A, conformance linter** (:mod:`.conformance`): AST +
  spec-introspection rules C1–C4 over ``ProtocolSpec`` handlers, the
  hand-written tensor twins, their adapters, and object-level ``Node``
  code.  The hard half of C4 is also the ``ProtocolSpec.compile()``
  gate (tpu/compiler.py ``SpecError``) — the conformance authority
  ROADMAP #3's arbitrary-user-protocol twin generation rides on.
* **Leg B, jaxpr auditor** (:mod:`.jaxpr_audit`): rules J0–J5 over the
  lowered StableHLO of every registered dispatch-site program,
  enumerated from ``tpu/telemetry.py DISPATCH_SITES`` via each
  engine's ``dispatch_site_programs()``.  ``DSLABS_SANITIZE=1`` runs
  it at engine build time and records findings as telemetry events.

CLI::

    python -m dslabs_tpu.analysis {conformance,jaxpr,all}
        [--waivers FILE] [--json] [--paths P ...]

Exit 1 on unwaived findings; the waiver file
(``.sanitizer-waivers``, format in :mod:`.core`) documents justified
exceptions.  docs/analysis.md is the field guide; ``make lint`` and
``run_tests.py --lint`` are the entry points CI and students use.
"""

from __future__ import annotations

import json as _json
import os
import sys
from typing import List, Optional, Sequence

from dslabs_tpu.analysis.core import (Finding, RULES, Waiver,  # noqa: F401
                                      apply_waivers, default_waiver_path,
                                      load_waivers, render_findings,
                                      repo_root)

__all__ = ["Finding", "Waiver", "RULES", "load_waivers", "apply_waivers",
           "render_findings", "default_waiver_path", "run_conformance",
           "run_jaxpr", "run_all", "sanitizer_summary", "main"]


def run_conformance(paths: Optional[Sequence[str]] = None,
                    waivers: Optional[str] = None) -> List[Finding]:
    """Leg A over the shipped tree (or ``paths``): AST lint + the C4
    spec introspection of every ``tpu/specs.py`` factory."""
    from dslabs_tpu.analysis import conformance as conf

    findings = conf.lint_paths(paths)
    if paths is None:
        findings += conf.lint_specs()
    return apply_waivers(findings, load_waivers(waivers))


def run_jaxpr(waivers: Optional[str] = None, deep: bool = True,
              mesh_devices: int = 2) -> List[Finding]:
    """Leg B over the CLI's standard engine set (pingpong twins,
    single-device + spill + sharded superstep + swarm), J5 retrace
    check included."""
    from dslabs_tpu.analysis.jaxpr_audit import (audit_search,
                                                 build_audit_engines)

    findings: List[Finding] = []
    for search in build_audit_engines(mesh_devices=mesh_devices):
        findings += audit_search(search, deep=deep)
    return apply_waivers(findings, load_waivers(waivers))


def run_all(paths: Optional[Sequence[str]] = None,
            waivers: Optional[str] = None) -> List[Finding]:
    return (run_conformance(paths, waivers=waivers)
            + run_jaxpr(waivers=waivers))


def sanitizer_summary(timeout: int = 180) -> dict:
    """The bench's ``sanitizer`` block (ISSUE 10 satellite): findings
    per leg + waived count, computed in a CPU-pinned SUBPROCESS so the
    bench parent never imports jax or touches the accelerator.  Never
    raises; failures come back as ``{"error": ...}``."""
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "dslabs_tpu.analysis", "all",
             "--json"],
            capture_output=True, text=True, timeout=timeout,
            cwd=repo_root(), env=env)
        data = _json.loads(proc.stdout.strip().splitlines()[-1])
        return {"conformance": data["conformance"],
                "jaxpr": data["jaxpr"], "waived": data["waived"],
                "findings": data["findings"]}
    except Exception as e:  # noqa: BLE001 — the bench JSON must land
        return {"error": f"{type(e).__name__}: {e}"}


# ------------------------------------------------------------------ CLI

_USAGE = """usage: python -m dslabs_tpu.analysis <command> [options]

  conformance   Leg A: protocol conformance linter (C1-C4)
  jaxpr         Leg B: jaxpr hot-path auditor (J0-J5)
  all           both legs

options:
  --waivers FILE   waiver file (default: <repo>/.sanitizer-waivers)
  --paths P [P..]  conformance: lint these files/dirs instead of the
                   shipped default set
  --json           one machine-readable JSON line instead of the report

exit code: 0 clean (waived findings allowed), 1 unwaived findings,
2 usage/crash.  Rule catalog + waiver format: docs/analysis.md.
"""


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("conformance", "jaxpr", "all"):
        print(_USAGE, file=sys.stderr)
        return 2
    cmd = argv[0]
    flags = argv[1:]
    waivers = None
    paths: Optional[List[str]] = None
    if "--waivers" in flags:
        waivers = flags[flags.index("--waivers") + 1]
    if "--paths" in flags:
        i = flags.index("--paths") + 1
        paths = []
        while i < len(flags) and not flags[i].startswith("--"):
            paths.append(flags[i])
            i += 1
    as_json = "--json" in flags

    findings: List[Finding] = []
    if cmd in ("conformance", "all"):
        findings += run_conformance(paths, waivers=waivers)
    if cmd in ("jaxpr", "all"):
        findings += run_jaxpr(waivers=waivers)

    live = [f for f in findings if not f.waived]
    if as_json:
        print(_json.dumps({
            "cmd": cmd,
            "findings": len(live),
            "waived": sum(1 for f in findings if f.waived),
            "conformance": sum(1 for f in live
                               if f.leg == "conformance"),
            "jaxpr": sum(1 for f in live if f.leg == "jaxpr"),
            "detail": [f.as_dict() for f in findings],
        }))
    else:
        print(render_findings(findings, header=f"sanitizer {cmd}"))
    return 1 if live else 0
