"""Leg B of the soundness sanitizer: the jaxpr hot-path auditor
(ISSUE 10).

Every perf win since PR 3 rests on invariants nothing checked until
now: supersteps make zero host round-trips, carries are donated so the
table/frontier update in place, programs stay int32, single-device
programs have no collectives, and the AOT-warmed executables keep
hitting the persistent compile cache.  This module audits those
invariants STATICALLY, over the lowered StableHLO of every registered
dispatch-site program — enumerated from the same site registry
telemetry keys its spans and profiler captures off
(``tpu/telemetry.py DISPATCH_SITES``) via each engine's
``dispatch_site_programs()``.  Lowering is trace-only: the audit never
compiles and never dispatches device work (the one exception is
SwarmSearch, whose carry shapes come from its real init program).

Rules (codes pinned by tests/test_analysis.py; catalog in core.RULES):

J0  registry coverage — an enumerated site missing from
    ``DISPATCH_SITES``, or a program that failed to lower: audit rot
    is itself a finding, never a silent skip.
J1  host callback — ``custom_call``-lowered Python callbacks
    (``jax.debug.print``, ``pure_callback``, ``io_callback``) or
    infeed/outfeed inside a device program: each one is a host
    round-trip per dispatch, exactly what the superstep refactor
    removed.
J2  float64 upcast — any ``f64`` tensor in the lowering: the engines
    are int32/uint32 end to end; an f64 doubles HBM traffic and is
    10x+ slower on TPU vector units.
J3  donation audit — a site the registry declares donated
    (``jit(..., donate_argnums=0)``) whose lowering kept NO
    input/output aliasing for a large carry: the table+frontier would
    reallocate every dispatch.
J4  unexpected collective — ``all_reduce``/``all_gather``/… in a
    program the registry declares single-device.
J5  retrace hazard — rebuilding the program from its builder lowers
    to DIFFERENT text: the compile-cache key churns, so every warden
    child / failover rung / re-level pays a fresh XLA compile the
    persistent cache was supposed to absorb.  (Deep check: run by the
    CLI and ``DSLABS_SANITIZE=full``; plain ``DSLABS_SANITIZE=1``
    skips the second trace at engine build time.)

``DSLABS_SANITIZE=1`` runs J0–J4 at engine build time and records
findings as telemetry ``sanitizer_finding`` events; off means off —
zero added dispatches, zero host transfers, one env read
(tests/test_telemetry.py overhead guard).
"""

from __future__ import annotations

import math
import os
import re
import warnings
from typing import Dict, List, Optional

from dslabs_tpu.analysis.core import (Finding, apply_waivers,
                                      default_waiver_path, load_waivers)

__all__ = ["audit_sites", "audit_search", "sanitize_engine",
           "sanitize_enabled", "build_audit_engines"]

_COLLECTIVES = ("stablehlo.all_reduce", "stablehlo.all_gather",
                "stablehlo.all_to_all", "stablehlo.collective_permute",
                "stablehlo.reduce_scatter",
                "stablehlo.collective_broadcast")
_ALIASING_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")
_F64_RE = re.compile(r"(?:<|x)f64\b")


def sanitize_enabled() -> str:
    """"" (off) | "on" (J0-J4) | "full" (adds the J5 double-trace)."""
    v = os.environ.get("DSLABS_SANITIZE", "").strip().lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    if v in ("2", "full", "deep"):
        return "full"
    return ""


def _arg_bytes(args) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += math.prod(shape) * dtype.itemsize
    return total


def _donate_min_bytes() -> int:
    try:
        return int(os.environ.get("DSLABS_SANITIZE_DONATE_MIN", "")
                   or 65536)
    except ValueError:
        return 65536


def _lower_text(fn, args) -> str:
    return fn.lower(*args).as_text()


def audit_sites(sites: Dict[str, dict], engine_name: str,
                deep: bool = False) -> List[Finding]:
    """Audit a ``{tag: entry}`` site map (the shape
    ``dispatch_site_programs()`` returns):

    entry = {"fn": jitted, "args": example (abstract ok) args,
             "donate": declared donate_argnums tuple,
             "multi": collectives expected?,
             "builder": optional () -> fresh jitted fn (J5)}
    """
    from dslabs_tpu.tpu.telemetry import DISPATCH_SITES

    findings: List[Finding] = []

    def emit(code: str, tag: str, message: str) -> None:
        findings.append(Finding(code=code, leg="jaxpr",
                                path=engine_name, obj=tag,
                                message=message))

    for tag, entry in sorted(sites.items()):
        meta = DISPATCH_SITES.get(tag)
        if meta is None:
            emit("J0", tag,
                 "dispatch site is not in telemetry.DISPATCH_SITES — "
                 "register it so spans, profiler captures, and this "
                 "audit cover it")
            meta = dict(hot=False, donated=bool(entry.get("donate")),
                        multi=bool(entry.get("multi")), program=True)
        try:
            text = _lower_text(entry["fn"], entry["args"])
        except Exception as e:  # noqa: BLE001 — an unlowerable site
            emit("J0", tag,     # program is audit rot, loudly
                 f"program failed to lower for audit: "
                 f"{type(e).__name__}: {e}")
            continue

        for line in text.splitlines():
            if ("custom_call" in line and "callback" in line.lower()) \
                    or "stablehlo.infeed" in line \
                    or "stablehlo.outfeed" in line:
                emit("J1", tag,
                     "host callback lowered into the device program "
                     f"({line.strip()[:120]}) — one host round-trip "
                     "per dispatch inside the hot loop")
                break
        if _F64_RE.search(text):
            emit("J2", tag,
                 "float64 tensor in the lowering — the engines are "
                 "int32/uint32 end to end; find the upcast (an "
                 "un-annotated np scalar or jnp.mean-style default)")
        donated = bool(entry.get("donate")) or meta.get("donated")
        if donated:
            nbytes = _arg_bytes(entry.get("args", ()))
            if nbytes >= _donate_min_bytes() and not any(
                    m in text for m in _ALIASING_MARKERS):
                emit("J3", tag,
                     f"declared donated but the lowering kept no "
                     f"input/output aliasing over ~{nbytes >> 10} KiB "
                     f"of carry — the buffers reallocate every "
                     f"dispatch (donate_argnums dropped, or shapes "
                     f"mismatch the donated outputs)")
        if not (entry.get("multi") or meta.get("multi")):
            hit = next((c for c in _COLLECTIVES if c in text), None)
            if hit is not None:
                emit("J4", tag,
                     f"{hit.split('.')[-1]} in a single-device "
                     f"program — a cross-device collective here means "
                     f"the program was built against the wrong mesh "
                     f"scope")
        if deep and entry.get("builder") is not None:
            try:
                text2 = _lower_text(entry["builder"](), entry["args"])
            except Exception as e:  # noqa: BLE001
                emit("J0", tag,
                     f"builder failed to rebuild the program for the "
                     f"retrace check: {type(e).__name__}: {e}")
                continue
            if text2 != text:
                emit("J5", tag,
                     "rebuilding the program lowers to different HLO "
                     "— the compile-cache key churns, so every warden "
                     "child / failover rung / knob re-level pays a "
                     "fresh XLA compile (fresh per-build constants or "
                     "id()-ordered iteration in the program builder)")
    return findings


def audit_search(search, deep: bool = False) -> List[Finding]:
    """Audit one built engine via its ``dispatch_site_programs()``."""
    sites = search.dispatch_site_programs()
    return audit_sites(sites, type(search).__name__, deep=deep)


def sanitize_engine(search) -> List[Finding]:
    """The ``DSLABS_SANITIZE`` build-time hook (called from the tail of
    each engine's ``__init__``): audit, apply waivers, record findings
    as telemetry events, warn once.  Never raises — a sanitizer crash
    must not take the engine down with it."""
    mode = sanitize_enabled()
    if not mode:
        return []
    try:
        findings = audit_search(search, deep=(mode == "full"))
        findings = apply_waivers(findings,
                                 load_waivers(default_waiver_path()))
    except Exception as e:  # noqa: BLE001 — never fatal at build time
        warnings.warn(f"DSLABS_SANITIZE: audit failed on "
                      f"{type(search).__name__}: "
                      f"{type(e).__name__}: {e}", RuntimeWarning,
                      stacklevel=2)
        return []
    tel = getattr(search, "_telemetry", None)
    if tel is not None:
        for f in findings:
            tel.event("sanitizer_finding", code=f.code, site=f.obj,
                      engine=f.path, message=f.message,
                      waived=f.waived)
    live = [f for f in findings if not f.waived]
    if live:
        warnings.warn(
            f"DSLABS_SANITIZE: {len(live)} jaxpr-audit finding(s) on "
            f"{type(search).__name__}: "
            + "; ".join(f"[{f.code}] {f.obj}" for f in live[:6]),
            RuntimeWarning, stacklevel=2)
    return findings


# ------------------------------------------------- CLI audit targets

def build_audit_engines(mesh_devices: int = 2,
                        with_swarm: bool = True,
                        with_spill: bool = True) -> List:
    """The CLI's standard audit set: pingpong twins on small caps —
    single-device engine (plus its spill variant), the sharded
    superstep engine, and the swarm — enough to cover every
    program-bearing site family in DISPATCH_SITES.  Built, never run
    (construction wraps jits lazily; only the audit's ``.lower()``
    traces them)."""
    from dslabs_tpu.tpu.engine import TensorSearch
    from dslabs_tpu.tpu.protocols.pingpong import make_pingpong_protocol
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    from dslabs_tpu.tpu.lanes import LaneSearch

    proto = make_pingpong_protocol(workload_size=2)
    # Capacity round 2 (ISSUE 15): a spec-compiled protocol with
    # declared domains + symmetry groups, so the packing.pack/unpack
    # codec programs and the symmetry.canonicalize pass register as
    # audit sites (the hand twins derive the identity descriptor and
    # register neither).
    from dslabs_tpu.tpu.specs import paxos_spec

    packed_proto = paxos_spec(3).compile()
    engines = [
        TensorSearch(proto, max_depth=8, frontier_cap=1 << 8,
                     visited_cap=1 << 10),
        TensorSearch(packed_proto, max_depth=8, frontier_cap=1 << 8,
                     visited_cap=1 << 10, symmetry=True),
        ShardedTensorSearch(proto, make_mesh(mesh_devices),
                            chunk_per_device=16, frontier_cap=1 << 8,
                            visited_cap=1 << 10, max_depth=8),
        # Batched job lanes (ISSUE 14): the lane superstep is the
        # multi-tenant hot path — audited like every other engine so
        # `analysis all` cannot silently skip it.
        LaneSearch(proto, n_lanes=2, frontier_cap=1 << 8,
                   visited_cap=1 << 10),
    ]
    if with_spill:
        from dslabs_tpu.tpu.spill import spill_manager_for_audit

        engines.append(TensorSearch(
            proto, max_depth=8, frontier_cap=1 << 8,
            visited_cap=1 << 10, spill=spill_manager_for_audit()))
    if with_swarm:
        from dslabs_tpu.tpu.swarm import SwarmSearch

        engines.append(SwarmSearch(
            proto, make_mesh(mesh_devices), walkers_per_device=8,
            max_steps=8, max_rounds=2, visited_cap=1 << 10))
    return engines
