"""The emulated real-time network: per-address inboxes.

Re-design of framework/tst/.../runner/Network.java:44-199.  Each node has an
Inbox = a FIFO message queue + a priority queue of timers ordered by wall-clock
deadline; blocking ``take()`` returns the next message immediately or waits
until the earliest timer is due, waking early when a sooner timer arrives.
Per-inbox received-message counters back the lab3 message-budget test.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Dict, Iterable, Optional

from dslabs_tpu.core.address import Address
from dslabs_tpu.testing.events import Event, MessageEnvelope, TimerEnvelope

__all__ = ["Network", "Inbox"]


class Inbox:

    def __init__(self):
        self._cond = threading.Condition()
        self._messages: deque = deque()
        self._timers: list = []  # heap of (end_ns, seq, TimerEnvelope)
        self._seq = itertools.count()
        self._interrupted = False
        self.num_messages_received = 0

    def send(self, envelope: MessageEnvelope) -> None:
        with self._cond:
            self._messages.append(envelope)
            self.num_messages_received += 1
            self._cond.notify()

    def set_timer(self, envelope: TimerEnvelope) -> None:
        envelope.start()
        with self._cond:
            heapq.heappush(self._timers, (envelope.end_ns, next(self._seq), envelope))
            self._cond.notify()  # may be earlier than the current wait target

    def take(self) -> Optional[Event]:
        """Block until a message is available or the earliest timer is due
        (Network.java:100-149).  Returns None when interrupted (the runner's
        shutdown path; the Java engine interrupts the node thread)."""
        with self._cond:
            while True:
                if self._interrupted:
                    return None
                if self._messages:
                    return self._messages.popleft()
                if self._timers:
                    end_ns, _, te = self._timers[0]
                    now = time.monotonic_ns()
                    if now >= end_ns:
                        heapq.heappop(self._timers)
                        return te
                    self._cond.wait(timeout=(end_ns - now) / 1e9)
                else:
                    self._cond.wait()

    def poll_message(self) -> Optional[MessageEnvelope]:
        with self._cond:
            return self._messages.popleft() if self._messages else None

    def poll_due_timer(self) -> Optional[TimerEnvelope]:
        with self._cond:
            if self._timers and time.monotonic_ns() >= self._timers[0][0]:
                return heapq.heappop(self._timers)[2]
            return None

    def interrupt(self) -> None:
        with self._cond:
            self._interrupted = True
            self._cond.notify_all()

    def clear_interrupt(self) -> None:
        with self._cond:
            self._interrupted = False

    def __len__(self) -> int:
        with self._cond:
            return len(self._messages) + len(self._timers)


class Network:

    def __init__(self):
        self._inboxes: Dict[Address, Inbox] = {}
        self._lock = threading.Lock()

    def add_inbox(self, address: Address) -> Inbox:
        with self._lock:
            return self._inboxes.setdefault(address.root_address(), Inbox())

    def remove_inbox(self, address: Address) -> None:
        with self._lock:
            self._inboxes.pop(address.root_address(), None)

    def inbox(self, address: Address) -> Optional[Inbox]:
        with self._lock:
            return self._inboxes.get(address.root_address())

    def send(self, envelope: MessageEnvelope) -> None:
        """Deliver to the destination inbox; silently dropped if the node does
        not exist (Network.java:178-180)."""
        inbox = self.inbox(envelope.to.root_address())
        if inbox is not None:
            inbox.send(envelope)

    def set_timer(self, envelope: TimerEnvelope) -> None:
        inbox = self.inbox(envelope.to.root_address())
        if inbox is not None:
            inbox.set_timer(envelope)

    def num_messages_received(self, address: Address) -> int:
        inbox = self.inbox(address)
        return inbox.num_messages_received if inbox else 0

    def total_messages_received(self) -> int:
        with self._lock:
            return sum(i.num_messages_received for i in self._inboxes.values())

    def addresses(self) -> Iterable[Address]:
        with self._lock:
            return list(self._inboxes.keys())
