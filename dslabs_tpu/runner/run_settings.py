"""Run settings: probabilistic message delivery on top of connectivity.

Re-design of framework/tst/.../runner/RunSettings.java:41-200.
``should_deliver`` = connectivity (TestSettings) then a Bernoulli draw with
rate resolved by priority: link > sender > receiver > global.  Self-addressed
messages always deliver.  ``network_unreliable(True)`` sets the global rate
to 0.5.  A rate > 1.0 is the reference's "explicitly reliable" placeholder.
"""

from __future__ import annotations

import random
from typing import Dict, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.testing.settings import TestSettings

__all__ = ["RunSettings"]

DEFAULT_UNRELIABLE_RATE = 0.5


class RunSettings(TestSettings):

    def __init__(self):
        super().__init__()
        self.wait_for_clients: bool = True
        self._link_rate: Dict[Tuple[Address, Address], float] = {}
        self._sender_rate: Dict[Address, float] = {}
        self._receiver_rate: Dict[Address, float] = {}
        self._network_rate: Optional[float] = None

    # ----------------------------------------------------------------- rates

    @staticmethod
    def _check_rate(rate: float) -> float:
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"Deliver rate must be in [0, 1]: {rate}")
        return rate

    def network_deliver_rate(self, rate: float) -> "RunSettings":
        self._network_rate = self._check_rate(rate)
        return self

    def network_unreliable(self, unreliable: bool) -> "RunSettings":
        if unreliable and self._network_rate is None:
            self._network_rate = DEFAULT_UNRELIABLE_RATE
        elif not unreliable:
            self._network_rate = None
        return self

    def link_deliver_rate(self, frm: Address, to: Address, rate: float) -> "RunSettings":
        self._link_rate[(frm.root_address(), to.root_address())] = self._check_rate(rate)
        return self

    def sender_deliver_rate(self, frm: Address, rate: float) -> "RunSettings":
        self._sender_rate[frm.root_address()] = self._check_rate(rate)
        return self

    def receiver_deliver_rate(self, to: Address, rate: float) -> "RunSettings":
        self._receiver_rate[to.root_address()] = self._check_rate(rate)
        return self

    def node_deliver_rate(self, node: Address, rate: float) -> "RunSettings":
        return (self.sender_deliver_rate(node, rate)
                .receiver_deliver_rate(node, rate))

    def _map_unreliable(self, mapping, key, unreliable: bool) -> "RunSettings":
        if unreliable:
            cur = mapping.get(key)
            if cur is None or cur > 1.0:
                mapping[key] = DEFAULT_UNRELIABLE_RATE
        else:
            mapping[key] = 2.0  # reliable placeholder (RunSettings.java:126)
        return self

    def link_unreliable(self, frm: Address, to: Address, unreliable: bool) -> "RunSettings":
        return self._map_unreliable(
            self._link_rate, (frm.root_address(), to.root_address()), unreliable)

    def sender_unreliable(self, frm: Address, unreliable: bool) -> "RunSettings":
        return self._map_unreliable(self._sender_rate, frm.root_address(), unreliable)

    def receiver_unreliable(self, to: Address, unreliable: bool) -> "RunSettings":
        return self._map_unreliable(self._receiver_rate, to.root_address(), unreliable)

    def node_unreliable(self, node: Address, unreliable: bool) -> "RunSettings":
        return (self.sender_unreliable(node, unreliable)
                .receiver_unreliable(node, unreliable))

    def reset_network(self) -> "RunSettings":
        self.reconnect()
        self._link_rate.clear()
        self._sender_rate.clear()
        self._receiver_rate.clear()
        self._network_rate = None
        return self

    # -------------------------------------------------------------- delivery

    def should_deliver(self, envelope) -> bool:
        frm = envelope.frm.root_address()
        to = envelope.to.root_address()
        if frm == to:
            return True
        if not super().should_deliver(envelope):
            return False
        link = (frm, to)
        if link in self._link_rate:
            rate = self._link_rate[link]
        elif frm in self._sender_rate:
            rate = self._sender_rate[frm]
        elif to in self._receiver_rate:
            rate = self._receiver_rate[to]
        else:
            rate = self._network_rate
        return rate is None or rate > 1.0 or random.random() < rate
