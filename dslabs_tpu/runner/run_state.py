"""RunState: the live, wall-clock system.

Re-design of framework/tst/.../runner/RunState.java:53-414.

* ``_setup_node`` wires a node's hooks to clone-on-send into the Network and
  to record a thrown-exception flag (RunState.java:95-122).
* Multi-threaded mode: one thread per node looping ``inbox.take()`` ->
  deliver, filtered by ``settings.should_deliver`` / timer gating
  (RunState.java:133-163).
* Single-threaded mode: round-robin delivering at most one message and one
  due timer per node per step (RunState.java:165-181).
* ``run``/``start``/``stop``/``wait_for`` lifecycle; nodes can be added and
  removed live (RunState.java:125-131, 193-383).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Dict, Iterable, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.node import Node, NodeConfig
from dslabs_tpu.runner.network import Network
from dslabs_tpu.runner.run_settings import RunSettings
from dslabs_tpu.testing.events import MessageEnvelope, TimerEnvelope
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.state import AbstractState
from dslabs_tpu.utils.structural import clone

LOG = logging.getLogger("dslabs.runner")

__all__ = ["RunState", "stop_active_run_states"]

_SLOW_HANDLER_WARN_S = 1.0

# Every RunState that starts registers here; the harness stops them all
# when a test TIMES OUT (tests run sequentially, so anything still active
# at that point belongs to the timed-out test).  The reference interrupts
# and joins node threads on timeout (RunState.java:340-383); abandoning
# the daemon thread used to leave its node threads mutating state and
# burning CPU under later tests (round-2 verdict, weak #5).
_ACTIVE: "weakref.WeakSet[RunState]" = weakref.WeakSet()


def stop_active_run_states() -> "Tuple[int, int]":
    """Cooperatively stop every running RunState; returns
    ``(stopped, stuck_threads)`` where ``stuck_threads`` counts node
    threads that survived their join timeout (the harness surfaces the
    count so a wedged handler is attributable, not a generic warning)."""
    stopped = stuck = 0
    for rs in list(_ACTIVE):
        if rs.running():
            rs.stop()
            stopped += 1
            stuck += rs.stuck_threads
    return stopped, stuck


class RunState(AbstractState):

    def __init__(self, generator: NodeGenerator):
        super().__init__(generator)
        self._network = Network()
        self._settings: Optional[RunSettings] = None
        self._threads: Dict[Address, threading.Thread] = {}
        self._running = False
        self._shutdown = threading.Event()
        self._exception_thrown = False
        self._lock = threading.RLock()
        self.stop_time: Optional[float] = None
        # Node threads that outlived their stop() join timeout (wedged
        # handlers); surfaced to the harness for timeout diagnostics.
        self.stuck_threads: int = 0

    # Live run state is never hashed/deduped; identity equality is fine and
    # avoids touching concurrently-mutating node state.
    def __eq__(self, other):
        return self is other

    def __hash__(self):
        return id(self)

    # ------------------------------------------------------------- accessors

    @property
    def network(self) -> Network:
        return self._network

    @property
    def exception_thrown(self) -> bool:
        return self._exception_thrown

    def timers(self, address: Address):
        raise NotImplementedError("RunState does not expose timer queues")

    # -------------------------------------------------------- engine contract

    def _setup_node(self, address: Address) -> None:
        node = self.node(address)
        self._network.add_inbox(address)
        self._config_node(address)
        node.init()
        with self._lock:
            if self._running:
                self._start_node_thread(address)

    def _ensure_node_config(self, address: Address) -> None:
        self._config_node(address)

    def _cleanup_node(self, address: Address) -> None:
        """Remove a node live: interrupt its thread and delete its inbox
        (RunState.java:125-131)."""
        with self._lock:
            self._threads.pop(address, None)
        inbox = self._network.inbox(address)
        if inbox is not None:
            inbox.interrupt()
        self._network.remove_inbox(address)

    def _config_node(self, address: Address) -> None:
        state = self

        def message_adder(frm: Address, to: Address, message) -> None:
            env = MessageEnvelope(frm, to, clone(message))  # clone-on-send
            state._network.send(env)

        def batch_message_adder(frm, tos, message) -> None:
            # Clone per destination: each inbox must own its copy (the
            # reference wires only the per-destination clone-on-send adder in
            # the runner, RunState.java:99-115).
            for to in tos:
                state._network.send(MessageEnvelope(frm, to, clone(message)))

        def timer_adder(frm: Address, timer, min_ms: int, max_ms: int) -> None:
            env = TimerEnvelope(frm, clone(timer), min_ms, max_ms)
            state._network.set_timer(env)

        def throwable_catcher(t: BaseException) -> None:
            LOG.exception("Node %s threw", address, exc_info=t)
            state._exception_thrown = True

        self.node(address).config(NodeConfig(
            message_adder=message_adder,
            batch_message_adder=batch_message_adder,
            timer_adder=timer_adder,
            throwable_catcher=throwable_catcher,
            log_exceptions=True))

    # -------------------------------------------------------------- delivery

    def _deliver(self, address: Address, event) -> None:
        node = self.node(address)
        if node is None:
            return
        start = time.monotonic()
        if isinstance(event, MessageEnvelope):
            if self._settings is None or self._settings.should_deliver(event):
                node.deliver_message(event.message, event.frm, event.to)
        else:
            if self._settings is None or self._settings.should_deliver_timer(event.to):
                node.deliver_timer(event.timer, event.to)
        elapsed = time.monotonic() - start
        if elapsed > _SLOW_HANDLER_WARN_S:
            LOG.warning("Handler on %s took %.2fs; handlers must not block",
                        address, elapsed)

    def _run_node_loop(self, address: Address) -> None:
        while not self._shutdown.is_set():
            inbox = self._network.inbox(address)
            if inbox is None:
                return  # node removed
            event = inbox.take()
            if event is None or self._shutdown.is_set():
                return
            self._deliver(address, event)

    def _start_node_thread(self, address: Address) -> None:
        t = threading.Thread(target=self._run_node_loop, args=(address,),
                             name=f"dslabs-node-{address}", daemon=True)
        self._threads[address] = t
        t.start()

    # ------------------------------------------------------------- lifecycle

    def start(self, settings: Optional[RunSettings] = None) -> None:
        """Start the system without blocking (multi-threaded mode)."""
        with self._lock:
            if self._running:
                raise RuntimeError("RunState already running")
            self._settings = settings or RunSettings()
            self._shutdown.clear()
            self._running = True
            self.stop_time = None
            _ACTIVE.add(self)
            for address in list(self.addresses()):
                inbox = self._network.inbox(address)
                if inbox is not None:
                    inbox.clear_interrupt()
                self._start_node_thread(address)

    def run(self, settings: Optional[RunSettings] = None) -> None:
        """Run until clients finish / the time budget elapses, then stop
        (RunState.java:223-276)."""
        settings = settings or RunSettings()
        if settings.single_threaded:
            self._run_single_threaded(settings)
            return
        self.start(settings)
        try:
            self.wait_for()
        finally:
            self.stop()

    def _run_single_threaded(self, settings: RunSettings) -> None:
        """Round-robin: at most one message and one due timer per node per
        sweep (RunState.java:165-181)."""
        self._settings = settings
        self._shutdown.clear()
        self._running = True
        self.stop_time = None
        _ACTIVE.add(self)
        start = time.monotonic()
        try:
            # The shutdown check makes a timed-out single-threaded run
            # stoppable from the harness (this loop runs IN the abandoned
            # test thread).
            while not self._shutdown.is_set():
                delivered_any = False
                for address in list(self.addresses()):
                    inbox = self._network.inbox(address)
                    if inbox is None:
                        continue
                    m = inbox.poll_message()
                    if m is not None:
                        self._deliver(address, m)
                        delivered_any = True
                    t = inbox.poll_due_timer()
                    if t is not None:
                        self._deliver(address, t)
                        delivered_any = True
                if self._done_condition(settings, start):
                    return
                if not delivered_any:
                    time.sleep(0.001)
        finally:
            self._running = False
            self.stop_time = time.monotonic()

    def _done_condition(self, settings: RunSettings, start: float) -> bool:
        if settings.wait_for_clients and self.client_workers_map:
            if all(w.done() for w in self.client_workers_map.values()):
                return True
        if settings.max_time_secs is not None:
            return time.monotonic() - start >= settings.max_time_secs
        if not (settings.wait_for_clients and self.client_workers_map):
            return True  # nothing to wait for
        return False

    def wait_for(self) -> None:
        """Wait for client workers (if configured) and/or the time budget
        (RunState.java:193-217)."""
        settings = self._settings or RunSettings()
        if settings.wait_for_clients and self.client_workers_map:
            deadline = (None if settings.max_time_secs is None
                        else time.monotonic() + settings.max_time_secs)
            for worker in list(self.client_workers_map.values()):
                timeout = (None if deadline is None
                           else max(0.0, deadline - time.monotonic()))
                worker.wait_until_done(timeout)
        elif settings.max_time_secs is not None:
            time.sleep(settings.max_time_secs)

    def stop(self) -> None:
        """Interrupt node threads and join them (RunState.java:340-383).

        A thread that survives the 2 s join is a wedged handler: its
        NAME AND NODE ADDRESS are logged (not a generic ">1s" line) and
        the count lands in ``self.stuck_threads`` so the harness can
        attribute a test timeout to the specific stuck node."""
        with self._lock:
            if not self._running:
                return
            self._shutdown.set()
            threads = list(self._threads.items())   # (address, thread)
            self._threads.clear()
            self._running = False
        for address in list(self.addresses()):
            inbox = self._network.inbox(address)
            if inbox is not None:
                inbox.interrupt()
        join_start = time.monotonic()
        for _, t in threads:
            t.join(timeout=2.0)
        stuck = [(a, t) for a, t in threads if t.is_alive()]
        self.stuck_threads = len(stuck)
        if stuck:
            LOG.warning(
                "%d node thread(s) still alive after stop: %s — "
                "handlers must not block",
                len(stuck),
                ", ".join(f"{t.name} (node {a})" for a, t in stuck))
        elif time.monotonic() - join_start > 1.0:
            LOG.warning("Node threads took >1s to stop; "
                        "handlers should not block")
        self.stop_time = time.monotonic()

    def running(self) -> bool:
        return self._running
