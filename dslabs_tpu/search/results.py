"""Search results carrier.

Re-design of framework/tst/.../search/SearchResults.java:34-88: first-writer-
wins result slots for invariant violation / goal match / exception, plus the
resolved end condition.
"""

from __future__ import annotations

import enum
import threading
from typing import List, Optional

from dslabs_tpu.testing.predicates import PredicateResult, StatePredicate

__all__ = ["EndCondition", "SearchResults"]


class EndCondition(enum.Enum):
    SPACE_EXHAUSTED = "SPACE_EXHAUSTED"
    TIME_EXHAUSTED = "TIME_EXHAUSTED"
    INVARIANT_VIOLATED = "INVARIANT_VIOLATED"
    GOAL_FOUND = "GOAL_FOUND"
    EXCEPTION_THROWN = "EXCEPTION_THROWN"


class SearchResults:

    discovered_count: int = 0
    # Tensor-backend exploration stats (0 on the object checker): beam-
    # style coverage truncations and visited-table treat-as-fresh
    # overflows (see dslabs_tpu/tpu/visited.py's overflow contract) are
    # surfaced here so callers can tell an exact exhaustion from a
    # degraded one.
    dropped: int = 0
    visited_overflow: int = 0

    def __init__(self, invariants: List[StatePredicate],
                 goals: List[StatePredicate]):
        self.invariants = list(invariants)
        self.goals = list(goals)
        self.end_condition: Optional[EndCondition] = None
        self._lock = threading.Lock()
        self._invariant_violating_state = None
        self._invariant_violated: Optional[PredicateResult] = None
        self._goal_matching_state = None
        self._goal_matched: Optional[PredicateResult] = None
        self._exceptional_state = None
        self._exception_signalled = False

    # First-writer-wins setters (SearchResults.java:48-80).  A None state is a
    # "signal" write used to stop other workers before minimization finishes;
    # the real state overwrites it.

    def invariant_violated(self, state, result: PredicateResult) -> None:
        with self._lock:
            if self._invariant_violating_state is None:
                self._invariant_violating_state = state
                self._invariant_violated = result

    def goal_found(self, state, result: PredicateResult) -> None:
        with self._lock:
            if self._goal_matching_state is None:
                self._goal_matching_state = state
                self._goal_matched = result

    def exception_thrown(self, state) -> None:
        with self._lock:
            self._exception_signalled = True
            if self._exceptional_state is None:
                self._exceptional_state = state

    @property
    def invariant_violating_state(self):
        return self._invariant_violating_state

    @property
    def invariant_violated_result(self) -> Optional[PredicateResult]:
        return self._invariant_violated

    @property
    def goal_matching_state(self):
        return self._goal_matching_state

    @property
    def goal_matched_result(self) -> Optional[PredicateResult]:
        return self._goal_matched

    @property
    def exceptional_state(self):
        return self._exceptional_state

    @property
    def exception_signalled(self) -> bool:
        return self._exception_signalled

    def terminal_found(self) -> bool:
        return (self._exception_signalled
                or self._invariant_violating_state is not None
                or self._goal_matching_state is not None)

    def __repr__(self) -> str:
        return f"SearchResults(end={self.end_condition})"
