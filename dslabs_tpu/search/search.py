"""The search drivers: BFS and random DFS over the system state graph.

Re-design of framework/tst/.../search/Search.java:63-583.  The per-state
pipeline (``check_state``) runs, in order: thrown exception -> invariant
violation -> goal match -> optional determinism/idempotence re-execution
checks -> prunes -> depth limit (Search.java:162-231; SURVEY §7.5).  Terminal
states stop the whole search; pruned states are not expanded.  The initial
state is checked too.

BFS explores one depth level at a time from an insertion-ordered frontier and
dedups successors at generation time against the search-equivalence relation
(Search.java:405-505).  BFS does NOT run the trace minimizer (its traces are
shortest by construction); RandomDFS minimizes its random deep probes
(checkState call sites Search.java:473, 492 vs 570).

This object-graph implementation is the semantic oracle; the TPU backend
(dslabs_tpu.tpu) vectorizes the same level-step and is diffed against this
one for verdict parity.
"""

from __future__ import annotations

import enum
import random
import time
from collections import deque
from typing import List, Optional

from dslabs_tpu.search.minimize import (minimize_exception_causing_trace,
                                        minimize_trace)
from dslabs_tpu.search.results import EndCondition, SearchResults
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.utils.check_logger import CheckLogger
from dslabs_tpu.utils.flags import GlobalSettings

__all__ = ["Search", "BFS", "RandomDFS", "bfs", "dfs"]


class StateStatus(enum.Enum):
    VALID = "VALID"
    TERMINAL = "TERMINAL"
    PRUNED = "PRUNED"


class Search:
    """Common driver: settings, results, time budget, status output."""

    def __init__(self, settings: Optional[SearchSettings]):
        self.settings = settings if settings is not None else SearchSettings()
        self.results = SearchResults(self.settings.invariants,
                                     self.settings.goals)
        self._start_time = 0.0
        self._last_status = 0.0

    # -------------------------------------------------------------- template

    def search_type(self) -> str:
        raise NotImplementedError

    def init_search(self, initial_state: SearchState) -> None:
        raise NotImplementedError

    def space_exhausted(self) -> bool:
        raise NotImplementedError

    def run_one_worker(self) -> None:
        """Explore one unit of work."""
        raise NotImplementedError

    def status(self, elapsed_secs: float) -> str:
        raise NotImplementedError

    # ---------------------------------------------------------------- engine

    def check_state(self, s: SearchState, should_minimize: bool) -> StateStatus:
        if s.thrown_exception is not None:
            if should_minimize:
                self.results.exception_thrown(None)
                s = minimize_exception_causing_trace(s)
            self.results.exception_thrown(s)
            return StateStatus.TERMINAL

        r = self.settings.invariant_violated(s)
        if r is not None:
            if should_minimize:
                self.results.invariant_violated(None, r)
                s = minimize_trace(s, r)
            self.results.invariant_violated(s, r)
            return StateStatus.TERMINAL

        r = self.settings.goal_matched(s)
        if r is not None:
            if should_minimize:
                self.results.goal_found(None, r)
                s = minimize_trace(s, r)
            self.results.goal_found(s, r)
            return StateStatus.TERMINAL

        if GlobalSettings.do_error_checks():
            previous = s.previous
            e = s.previous_event
            if previous is not None:
                # Determinism: re-execute the event and compare.
                if s != previous.step_event(e, self.settings, skip_checks=True):
                    CheckLogger.not_deterministic(e, previous)
                if GlobalSettings.do_all_error_checks():
                    from dslabs_tpu.testing.events import MessageEnvelope
                    if (isinstance(e, MessageEnvelope)
                            and s != s.step_event(e, self.settings, skip_checks=True)):
                        CheckLogger.not_idempotent(e, previous)

        if self.settings.should_prune(s):
            return StateStatus.PRUNED

        if (self.settings.depth_limited()
                and s.depth >= self.settings.max_depth):
            return StateStatus.PRUNED

        return StateStatus.VALID

    def _time_exhausted(self) -> bool:
        from dslabs_tpu.utils.flags import GlobalSettings

        return (self.settings.max_time_secs is not None
                and time.monotonic() - self._start_time
                >= self.settings.max_time_secs
                * GlobalSettings.time_scale)

    def _maybe_print_status(self) -> None:
        if not self.settings.should_output_status():
            return
        now = time.monotonic()
        if now - self._last_status >= self.settings.output_freq_secs:
            self._last_status = now
            print(self.status(now - self._start_time))

    def run(self, initial_state: SearchState) -> SearchResults:
        self._start_time = time.monotonic()
        self._last_status = self._start_time
        self.init_search(initial_state)

        # Sequential worker loop.  The Java engine runs a one-depth-at-a-time
        # thread pool (Search.java:240-347); under CPython the object oracle
        # is sequential — the *parallel* engine is the TPU backend, where one
        # BFS level is one vmapped XLA program (dslabs_tpu/tpu/engine.py).
        while (not self.results.terminal_found()
               and not self.space_exhausted()
               and not self._time_exhausted()):
            self.run_one_worker()
            self._maybe_print_status()

        if self.settings.should_output_status():
            print(self.status(max(time.monotonic() - self._start_time, 1e-9)))
            print("Search finished.")

        # End-condition resolution (Search.java:368-383).
        if self.results.exceptional_state is not None or \
                self.results.exception_signalled:
            self.results.end_condition = EndCondition.EXCEPTION_THROWN
        elif self.results.invariant_violating_state is not None:
            self.results.end_condition = EndCondition.INVARIANT_VIOLATED
        elif self.results.goal_matching_state is not None:
            self.results.end_condition = EndCondition.GOAL_FOUND
        elif self.space_exhausted():
            self.results.end_condition = EndCondition.SPACE_EXHAUSTED
        else:
            self.results.end_condition = EndCondition.TIME_EXHAUSTED
        if hasattr(self, "_discovered"):
            self.results.discovered_count = len(self._discovered)
        return self.results


class BFS(Search):

    def __init__(self, settings: Optional[SearchSettings]):
        super().__init__(settings)
        self._queue: deque = deque()
        self._discovered: set = set()
        self.states_explored = 0
        self.max_depth_seen = 0
        self._initial_depth = 0

    def search_type(self) -> str:
        return "breadth-first"

    def status(self, elapsed_secs: float) -> str:
        return (f"Explored: {self.states_explored}, "
                f"Depth: {self.max_depth_seen} "
                f"({elapsed_secs:.2f}s, "
                f"{self.states_explored / elapsed_secs / 1000.0:.2f}K states/s)")

    def init_search(self, initial_state: SearchState) -> None:
        self._queue.append(initial_state)
        self._discovered.add(initial_state.search_equivalence_key())
        self.states_explored = 0
        self.max_depth_seen = initial_state.depth
        self._initial_depth = initial_state.depth

    def space_exhausted(self) -> bool:
        return not self._queue

    def run_one_worker(self) -> None:
        node = self._queue.popleft()
        self._explore(node)

    def _explore(self, node: SearchState) -> None:
        if node.depth == self._initial_depth:
            self.states_explored += 1
            if self.check_state(node, False) is StateStatus.TERMINAL:
                return

        for event in node.events(self.settings):
            successor = node.step_event(event, self.settings, skip_checks=True)
            if successor is None:
                continue
            key = successor.search_equivalence_key()
            if key in self._discovered:
                continue
            self._discovered.add(key)

            if successor.depth > self.max_depth_seen:
                self.max_depth_seen = successor.depth
            self.states_explored += 1

            status = self.check_state(successor, False)
            if status is StateStatus.TERMINAL:
                return
            if status is StateStatus.PRUNED:
                continue
            self._queue.append(successor)

            # Bail promptly on time exhaustion inside huge levels.
            if self.states_explored % 1024 == 0 and self._time_exhausted():
                return


class RandomDFS(Search):

    def __init__(self, settings: Optional[SearchSettings]):
        super().__init__(settings)
        self._initial: Optional[SearchState] = None
        self.states_explored = 0
        self.probes = 0

    def search_type(self) -> str:
        return "random depth-first"

    def status(self, elapsed_secs: float) -> str:
        return (f"Explored: {self.states_explored}, "
                f"Num Probes: {self.probes} "
                f"({elapsed_secs:.2f}s, "
                f"{self.states_explored / elapsed_secs / 1000.0:.2f}K explored/s)")

    def init_search(self, initial_state: SearchState) -> None:
        self._initial = initial_state
        self.probes = 0
        self.states_explored = 0

    def space_exhausted(self) -> bool:
        return False  # random probes never exhaust the space

    def run_one_worker(self) -> None:
        """One random probe from the initial state (Search.java:557-581)."""
        self.probes += 1
        self.states_explored += 1
        current = self._initial
        while current is not None:
            nxt = None
            events = current.events(self.settings)
            random.shuffle(events)
            for event in events:
                s = current.step_event(event, self.settings, skip_checks=True)
                if s is None:
                    continue
                self.states_explored += 1
                status = self.check_state(s, True)
                if status is StateStatus.TERMINAL:
                    return
                if status is StateStatus.PRUNED:
                    continue
                nxt = s
                break
            current = nxt
            if self._time_exhausted():
                return


def bfs(initial_state: SearchState,
        settings: Optional[SearchSettings] = None) -> SearchResults:
    """BFS entry point (Search.bfs, Search.java:390-402).  The search
    STRATEGY is selectable via ``GlobalSettings.search_backend``
    (``run_tests.py --search-backend tensor``): the tensor strategy runs
    the same state + settings on the TPU engine through the lab's
    protocol twin (tpu/backend.py) and fails loudly when no twin
    exists — it never silently falls back to the object checker."""
    if GlobalSettings.search_backend == "tensor":
        from dslabs_tpu.tpu.backend import tensor_bfs

        return tensor_bfs(initial_state, settings)
    return BFS(settings).run(initial_state)


def dfs(initial_state: SearchState,
        settings: Optional[SearchSettings] = None) -> SearchResults:
    if GlobalSettings.search_backend == "tensor":
        from dslabs_tpu.tpu.backend import tensor_dfs

        return tensor_dfs(initial_state, settings)
    return RandomDFS(settings).run(initial_state)
