"""Trace minimization: shrink a violating trace to a short witness.

Re-design of framework/tst/.../search/TraceMinimizer.java:32-109.  Walk the
parent chain from the end state; for each event, try re-playing the remaining
suffix without it — keep the drop if the end state still produces the same
predicate result (same truth value, or for exception traces, an exception of
the same class).  Iterate to fixpoint.

Replay uses default settings (all delivery permitted) with per-event validity
checks enabled, stopping at the first inapplicable event — matching
``applyEvents`` (TraceMinimizer.java:95-108).
"""

from __future__ import annotations

from typing import List, Optional

from dslabs_tpu.testing.predicates import PredicateResult, StatePredicate

__all__ = ["minimize_trace", "minimize_exception_causing_trace"]


def _apply_events(initial_state, events: List):
    s = initial_state
    for e in events:
        nxt = s.step_event(e, None, skip_checks=False)
        if nxt is None:
            break
        s = nxt
    return s


def _state_matches(s, r: PredicateResult) -> bool:
    if s is None:
        return False
    if r.exception_thrown:
        return r.predicate.check(s).exception_thrown
    r2 = r.predicate.test(s, expected=not r.value)
    return r2 is not None and not r2.exception_thrown


def minimize_trace(state, expected_result: PredicateResult):
    shortened = True
    while shortened:
        shortened = False
        events: List = []
        s = state
        while s.previous is not None:
            test = _apply_events(s.previous, events)
            if _state_matches(test, expected_result):
                shortened = True
                state = test
            else:
                events.insert(0, s.previous_event)
            s = s.previous
    return state


def minimize_exception_causing_trace(state):
    """Minimize preserving 'an exception of the same class was thrown'
    (TraceMinimizer.java:69-93)."""
    exception = state.thrown_exception
    assert exception is not None
    exc_cls = type(exception)

    def same_class(s) -> bool:
        e = getattr(s, "thrown_exception", None)
        return e is not None and type(e) is exc_cls

    pred = StatePredicate(f"{exc_cls.__name__} thrown", same_class)
    r = pred.check(state)
    assert r.value
    return minimize_trace(state, r)
