"""Trace replay as a search: step a fixed event list, checking each state.

Re-design of framework/tst/.../junit/TraceReplaySearch.java:35-107.  Used by
the saved-trace regression suite; pruning is not allowed during replay.
"""

from __future__ import annotations

from typing import List, Optional

from dslabs_tpu.search.search import Search, StateStatus
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.search.settings import SearchSettings
from dslabs_tpu.testing.events import Event

__all__ = ["TraceReplaySearch", "replay_trace"]


class TraceReplaySearch(Search):

    def __init__(self, settings: Optional[SearchSettings], history: List[Event]):
        super().__init__(settings)
        if self.settings.prunes:
            raise ValueError("Trace replay does not allow prune predicates")
        self._history = history
        self._initial: Optional[SearchState] = None
        self._done = False

    def search_type(self) -> str:
        return "trace replay"

    def status(self, elapsed_secs: float) -> str:
        return f"Replayed {len(self._history)} events ({elapsed_secs:.2f}s)"

    def init_search(self, initial_state: SearchState) -> None:
        self._initial = initial_state

    def space_exhausted(self) -> bool:
        return self._done

    def run_one_worker(self) -> None:
        state = self._initial
        if self.check_state(state, False) is StateStatus.TERMINAL:
            self._done = True
            return
        for event in self._history:
            nxt = state.step_event(event, self.settings, skip_checks=True)
            if nxt is None:
                break
            state = nxt
            if self.check_state(state, False) is StateStatus.TERMINAL:
                self._done = True
                return
        self._done = True


def replay_trace(initial_state: SearchState, history: List[Event],
                 settings: Optional[SearchSettings] = None):
    return TraceReplaySearch(settings, history).run(initial_state)
