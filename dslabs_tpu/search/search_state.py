"""SearchState: one vertex of the global system state graph.

Re-design of framework/tst/.../search/SearchState.java:69-631.  The semantics
the TPU backend must reproduce bit-for-bit (SURVEY §7):

  * The network is a **set** of (from, to, message) envelopes: duplicate sends
    collapse; delivering a message does NOT remove it (drop/dup/reorder are
    modeled implicitly by which events a path chooses to deliver).
  * ``dropped_network`` holds temporarily ignored messages that are not
    enumerable as events but still count toward state equality.
  * Successor construction clones only the stepped node and its timer queue
    (copy-on-write); message/timer payloads are cloned on send and again on
    delivery.
  * Search equivalence = state equality (nodes + network∪dropped + timers)
    + thrown-exception equality + (when drops are present) live-network
    equality — the wrapper at SearchState.java:576-619.

Implementation notes: the network uses an insertion-ordered dict-as-set so
event enumeration is deterministic; Java's HashSet order is hash-dependent,
which only affects tie-breaking among equally valid verdicts.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple, Union

from dslabs_tpu.core.address import Address
from dslabs_tpu.core.node import Node, NodeConfig
from dslabs_tpu.testing.client_worker import ClientWorker
from dslabs_tpu.testing.events import Event, MessageEnvelope, TimerEnvelope
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.state import AbstractState
from dslabs_tpu.utils.structural import clone, sfreeze

__all__ = ["SearchState"]


def _exc_key(e: Optional[BaseException]):
    if e is None:
        return None
    return (type(e).__qualname__, tuple(repr(a) for a in e.args))


class SearchState(AbstractState):

    def __init__(self, generator: NodeGenerator):
        super().__init__(generator)
        self._network: Dict[MessageEnvelope, None] = {}
        self._dropped: Dict[MessageEnvelope, None] = {}
        self._timers: Dict[Address, "TimerQueue"] = {}
        self._previous: Optional["SearchState"] = None
        self._previous_event: Optional[Event] = None
        self._depth = 0
        self._thrown_exception: Optional[BaseException] = None
        self._new_messages: List[MessageEnvelope] = []
        self._new_timers: List[TimerEnvelope] = []

    # ----------------------------------------------------------- construction

    @classmethod
    def _successor(cls, previous: "SearchState", address_to_clone: Address,
                   event: Event) -> "SearchState":
        """COW successor: share all nodes but ``address_to_clone``; copy the
        network sets shallowly and that node's timer queue
        (SearchState.java:104-122)."""
        from dslabs_tpu.search.timer_queue import TimerQueue
        ns: SearchState = cls._cow_copy(previous, address_to_clone)
        ns._network = dict(previous._network)
        ns._dropped = dict(previous._dropped)
        ns._timers = dict(previous._timers)
        ns._previous = previous
        ns._previous_event = event
        ns._depth = previous._depth + 1
        ns._thrown_exception = None
        ns._new_messages = []
        ns._new_timers = []
        ns._timers[address_to_clone] = TimerQueue(ns._timers.get(address_to_clone))
        ns._config_node(address_to_clone)
        return ns

    def shallow_clone(self) -> "SearchState":
        """Shallow COW clone sharing nodes and the parent pointer
        (SearchState.java:126-151); used by staged searches to tweak
        network/drop sets without disturbing the original."""
        ns: SearchState = type(self)._cow_copy(self, _NO_ADDRESS)
        ns._network = dict(self._network)
        ns._dropped = dict(self._dropped)
        ns._timers = dict(self._timers)
        ns._previous = self._previous
        ns._previous_event = self._previous_event
        ns._depth = self._depth
        ns._thrown_exception = self._thrown_exception
        ns._new_messages = list(self._new_messages)
        ns._new_timers = list(self._new_timers)
        return ns

    # -------------------------------------------------------------- equality

    def _eq_fields(self):
        f = super()._eq_fields()
        f["network"] = set(self._network) | set(self._dropped)
        f["timers"] = self._timers
        return f

    def search_equivalence_key(self):
        """Hashable key implementing search equivalence
        (SearchState.java:576-619): base equality + exception + live network
        when drops are in play."""
        base = (
            sfreeze(self.servers),
            sfreeze(self.client_workers_map),
            sfreeze(self.clients),
            frozenset(sfreeze(m) for m in self._network) | frozenset(
                sfreeze(m) for m in self._dropped),
            sfreeze(self._timers),
            _exc_key(self._thrown_exception),
        )
        if self._dropped:
            return base + (frozenset(sfreeze(m) for m in self._network),)
        return base

    # ------------------------------------------------------------- accessors

    @property
    def previous(self) -> Optional["SearchState"]:
        return self._previous

    @property
    def previous_event(self) -> Optional[Event]:
        return self._previous_event

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def thrown_exception(self) -> Optional[BaseException]:
        return self._thrown_exception

    @property
    def new_messages(self) -> List[MessageEnvelope]:
        return self._new_messages

    @property
    def new_timers(self) -> List[TimerEnvelope]:
        return self._new_timers

    def network(self) -> Iterable[MessageEnvelope]:
        """Union of live and dropped messages (state-equality view)."""
        yield from self._network
        yield from self._dropped

    def live_network(self) -> Iterable[MessageEnvelope]:
        return iter(self._network)

    def timers(self, address: Address):
        return self._timers[address.root_address()]

    # -------------------------------------------------------- engine contract

    def _setup_node(self, address: Address) -> None:
        from dslabs_tpu.search.timer_queue import TimerQueue
        node = self.node(address)
        if isinstance(node, ClientWorker) and not node.record_commands_and_results:
            raise RuntimeError(
                "Cannot add a ClientWorker that does not store results to SearchState.")
        self._timers[address] = TimerQueue()
        self._config_node(address)
        node.init()

    def _ensure_node_config(self, address: Address) -> None:
        self._config_node(address)

    def _cleanup_node(self, address: Address) -> None:
        raise RuntimeError("Cannot remove nodes from search state.")

    def _config_node(self, address: Address) -> None:
        """Wire send/set/throw hooks into the node (SearchState.java:189-224):
        messages are cloned on send and inserted set-wise; timers appended to
        the owner's queue; exceptions recorded on this state."""
        state = self

        def message_adder(frm: Address, to: Address, message) -> None:
            env = MessageEnvelope(frm, to, clone(message))
            state._network[env] = None
            state._new_messages.append(env)

        def batch_message_adder(frm: Address, tos: Tuple[Address, ...], message) -> None:
            m = clone(message)
            for to in tos:
                env = MessageEnvelope(frm, to, m)
                state._network[env] = None
                state._new_messages.append(env)

        def timer_adder(frm: Address, timer, min_ms: int, max_ms: int) -> None:
            env = TimerEnvelope(frm, clone(timer), min_ms, max_ms)
            state._timers[env.to.root_address()].add(env)
            state._new_timers.append(env)

        def throwable_catcher(t: BaseException) -> None:
            assert state._thrown_exception is None
            state._thrown_exception = t

        self.node(address).config(NodeConfig(
            message_adder=message_adder,
            batch_message_adder=batch_message_adder,
            timer_adder=timer_adder,
            throwable_catcher=throwable_catcher,
            log_exceptions=False))

    # ---------------------------------------------------------------- events

    def events(self, settings=None) -> List[Event]:
        """Enumerate deliverable events (SearchState.java:226-252): live
        messages whose destination exists and passes ``should_deliver``, then
        deliverable timers per node, gated by timer delivery settings."""
        from dslabs_tpu.search.settings import SearchSettings
        if settings is None:
            settings = SearchSettings()
        events: List[Event] = []
        for message in self._network:
            if (self.has_node(message.to.root_address())
                    and settings.should_deliver(message)):
                events.append(message)
        for address in self.addresses():
            if settings.should_deliver_timer(address):
                events.extend(self._timers[address].deliverable())
        return events

    def step(self, settings=None) -> List["SearchState"]:
        return [self.step_event(e, settings, skip_checks=True)
                for e in self.events(settings)]

    def step_event(self, event: Event, settings=None,
                   skip_checks: bool = False) -> Optional["SearchState"]:
        if isinstance(event, MessageEnvelope):
            return self.step_message(event, settings, skip_checks)
        return self.step_timer(event, settings, skip_checks)

    def step_message(self, message: MessageEnvelope, settings=None,
                     skip_checks: bool = False) -> Optional["SearchState"]:
        from dslabs_tpu.search.settings import SearchSettings
        if settings is None:
            settings = SearchSettings()
        to = message.to.root_address()
        if not self.has_node(to):
            return None
        if not skip_checks and not (message in self._network
                                    and settings.should_deliver(message)):
            return None
        ns = SearchState._successor(self, to, message)
        # Deliver a *clone* of the payload; the message stays in the network
        # ("Just handle, don't remove" — SearchState.java:300).
        nm = clone(message.message)
        ns.node(to).deliver_message(nm, message.frm, message.to)
        return ns

    def can_step_timer(self, timer: TimerEnvelope, settings=None) -> bool:
        from dslabs_tpu.search.settings import SearchSettings
        if settings is None:
            settings = SearchSettings()
        to = timer.to.root_address()
        return (self.has_node(to) and settings.should_deliver_timer(to)
                and self._timers[to].is_deliverable(timer))

    def step_timer(self, timer: TimerEnvelope, settings=None,
                   skip_checks: bool = False) -> Optional["SearchState"]:
        to = timer.to.root_address()
        if not self.has_node(to):
            return None
        if not skip_checks and not self.can_step_timer(timer, settings):
            return None
        ns = SearchState._successor(self, to, timer)
        nt = clone(timer.timer)
        ns.node(to).deliver_timer(nt, timer.to)
        ns._timers[to].remove(timer)  # firing consumes the timer
        return ns

    # ----------------------------------------------------------------- drops

    def _record_staged_op(self, op: tuple) -> None:
        """Mirror a staged network mutation into this state's tensor
        provenance (tpu/backend.py), so the next tensor-backend phase can
        re-derive its twin root by replaying the same op.  Ops on a state
        with no provenance yet (e.g. drop_pending_messages on the pristine
        initial state) accumulate in ``_staged_ops`` and are picked up by
        the backend's depth-0 path."""
        tp = getattr(self, "_tensor_provenance", None)
        if tp is not None:
            tp.history.append(op)
        else:
            if not hasattr(self, "_staged_ops"):
                self._staged_ops = []
            self._staged_ops.append(op)

    def drop_pending_messages(self) -> None:
        """Temporarily ignore all pending messages (used by staged searches,
        SearchState.java:534-541)."""
        self._dropped.update(self._network)
        self._network.clear()
        self._record_staged_op(("drop",))

    def undrop_messages(self) -> None:
        self._network.update(self._dropped)
        self._record_staged_op(("undrop_all",))

    def undrop_messages_from(self, address: Address) -> None:
        for m in self._dropped:
            if m.frm == address:
                self._network[m] = None
        self._record_staged_op(("undrop_from", str(address.root_address())))

    def undrop_messages_to(self, address: Address) -> None:
        for m in self._dropped:
            if m.to == address:
                self._network[m] = None
        self._record_staged_op(("undrop_to", str(address.root_address())))

    # ---------------------------------------------------------------- traces

    def trace(self) -> List["SearchState"]:
        out: List[SearchState] = []
        cur: Optional[SearchState] = self
        while cur is not None:
            out.append(cur)
            cur = cur._previous
        out.reverse()
        return out

    def print_trace(self, out=None) -> None:
        import sys
        out = out or sys.stderr
        for state in self.trace():
            if state._previous_event is not None:
                print(f"\t{state._previous_event}", file=out)
            print(state, file=out)

    def __repr__(self) -> str:
        nodes = ", ".join(f"{a}={self.node(a)!r}" for a in self.addresses())
        return (f"State(nodes={{{nodes}}}, network={list(self.network())}, "
                f"timers={self._timers})")


class _NoAddress:
    def root_address(self):
        return self


_NO_ADDRESS = _NoAddress()
