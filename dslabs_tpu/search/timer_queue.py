"""Logical-time timer queue for one node — the model's only ordering rule.

Re-design of framework/tst/.../search/TimerQueue.java:34-134.  In an
asynchronous system the sole restriction on timer delivery is: if a node set
timers t1 then t2 and ``t2.min >= t1.max``, t1 must fire before t2.  So a
timer t at position i is deliverable iff ``t.min < min(max of all earlier
timers in the queue)``; the first timer is always deliverable.

Firing removes exactly one matching timer (equality ignores sampled lengths,
TimerEnvelope equality semantics).
"""

from __future__ import annotations

from typing import Iterator, List

from dslabs_tpu.testing.events import TimerEnvelope
from dslabs_tpu.utils.structural import StructEq

__all__ = ["TimerQueue"]


class TimerQueue(StructEq):

    def __init__(self, other: "TimerQueue" = None):
        self.timers: List[TimerEnvelope] = list(other.timers) if other else []

    def add(self, envelope: TimerEnvelope) -> None:
        self.timers.append(envelope)

    def deliverable(self) -> Iterator[TimerEnvelope]:
        """Yield deliverable timers in queue order.

        Matches the reference iterator (TimerQueue.java:66-105): tracks the
        running minimum of preceding ``max`` bounds; a timer whose ``min`` is
        >= that bound cannot overtake and is skipped (and everything behind a
        skipped timer still compares against the same bound)."""
        min_max = None
        for te in self.timers:
            if min_max is not None and te.min_ms >= min_max:
                continue
            yield te
            if min_max is None or te.max_ms < min_max:
                min_max = te.max_ms

    def is_deliverable(self, envelope: TimerEnvelope) -> bool:
        """Membership + the overtaking constraint (TimerQueue.java:107-118):
        walk the queue; if we meet an equal timer first it is deliverable; if
        we first meet an earlier timer te with ``envelope.min >= te.max``, it
        is not."""
        for te in self.timers:
            if te == envelope:
                return True
            if envelope.min_ms >= te.max_ms:
                return False
        return False

    def remove(self, envelope: TimerEnvelope) -> None:
        self.timers.remove(envelope)

    def __iter__(self) -> Iterator[TimerEnvelope]:
        return iter(self.timers)

    def __len__(self) -> int:
        return len(self.timers)

    def __repr__(self) -> str:
        return repr(self.timers)
