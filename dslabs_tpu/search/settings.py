"""Search settings: prunes, goals, depth limit, status output.

Re-design of framework/tst/.../search/SearchSettings.java:43-199.

Exception policy (SURVEY §7.9): prune predicates that throw are treated as
pruned (the safe direction); goal predicates that throw are logged and
ignored; invariant exceptions (handled in TestSettings.invariants_hold via the
search layer) count as violations.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

from dslabs_tpu.testing.predicates import PredicateResult, StatePredicate
from dslabs_tpu.testing.settings import TestSettings

LOG = logging.getLogger("dslabs.search")

__all__ = ["SearchSettings"]


class SearchSettings(TestSettings):

    def __init__(self):
        super().__init__()
        self.prunes: List[StatePredicate] = []
        self.goals: List[StatePredicate] = []
        self.max_depth: int = -1
        self.num_threads: int = os.cpu_count() or 1
        self.output_freq_secs: float = -1

    # fluent helpers -------------------------------------------------------

    def clear(self) -> "SearchSettings":
        """Full reset (SearchSettings.java's clear(): invariants, goals,
        prunes, network matrix, timer gating, depth) keeping only the time
        budget defaults — used between staged-search phases
        (PaxosTest.java:1063)."""
        self.__init__()
        return self

    def add_prune(self, predicate: StatePredicate) -> "SearchSettings":
        self.prunes.append(predicate)
        return self

    def clear_prunes(self) -> "SearchSettings":
        self.prunes.clear()
        return self

    def add_goal(self, predicate: StatePredicate) -> "SearchSettings":
        self.goals.append(predicate)
        return self

    def clear_goals(self) -> "SearchSettings":
        self.goals.clear()
        return self

    def set_max_depth(self, depth: int) -> "SearchSettings":
        self.max_depth = depth
        return self

    def depth_limited(self) -> bool:
        return self.max_depth >= 0

    def should_output_status(self) -> bool:
        return self.output_freq_secs > 0

    # evaluation -----------------------------------------------------------

    def should_prune(self, state) -> bool:
        """Any prune matches => pruned; a throwing prune is logged and treated
        as pruned (SearchSettings.java:77-102)."""
        for p in self.prunes:
            r = p.test(state, expected=False)
            if r is None:
                continue
            if r.exception_thrown:
                LOG.error(r.error_message())
            return True
        return False

    def goal_matched(self, state) -> Optional[PredicateResult]:
        """First matching goal's result; throwing goals logged and skipped
        (SearchSettings.java:104-135)."""
        for p in self.goals:
            r = p.test(state, expected=False)
            if r is None:
                continue
            if r.exception_thrown:
                LOG.error(r.error_message())
                continue
            return r
        return None
