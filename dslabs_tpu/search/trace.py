"""Persistent traces and human-readable trace reordering.

Re-design of framework/tst/.../search/SerializableTrace.java:59-254 and the
causal reordering in SearchState.humanReadableTrace (SearchState.java:373-474).

A saved trace = (event history, invariants, node generator, server addresses,
client-worker (address, workload) pairs, lab/part/test metadata), pickled to
``traces/lab<id>[part<p>]_<timestamp>.trace``.  ``initial_state``/``end_state``
reconstruct by replay; loading tolerates stale traces that no longer
unpickle (skipped with a warning).
"""

from __future__ import annotations

import logging
import os
import time

# cloudpickle serializes lambdas/closures by value — the analog of the
# reference's SerializableFunction/Supplier SAM types (utils/Serializable*.java)
# that let predicates, workloads and generators survive trace serialization.
import cloudpickle as pickle
from typing import List, Optional, Tuple

from dslabs_tpu.core.address import Address
from dslabs_tpu.search.search_state import SearchState
from dslabs_tpu.testing.events import Event, MessageEnvelope
from dslabs_tpu.testing.generator import NodeGenerator
from dslabs_tpu.testing.predicates import StatePredicate
from dslabs_tpu.testing.workload import Workload

LOG = logging.getLogger("dslabs.trace")

__all__ = ["SerializableTrace", "human_readable_trace",
           "human_readable_trace_end_state", "save_trace", "TRACES_DIR"]

TRACES_DIR = "traces"


def human_readable_trace(state: SearchState) -> List[SearchState]:
    """Topologically reorder a trace into causal order for display.

    Builds the happens-before graph over events: an event depends on (a) the
    step that first sent its message and (b) the previous step at the same
    node; then replays a depth-first linearization
    (SearchState.java:373-474)."""
    original = state.trace()

    class GNode:
        __slots__ = ("event", "next", "previous")

        def __init__(self, event):
            self.event = event
            self.next: List[GNode] = []
            self.previous: List[GNode] = []

    when_sent = {}
    last_step = {}
    init_steps: List[GNode] = []

    for s in original[1:]:
        event = s.previous_event
        gn = GNode(event)
        if isinstance(event, MessageEnvelope):
            sender = when_sent.get(event)
            if sender is not None:
                sender.next.append(gn)
                gn.previous.append(sender)
        a = event.location_root_address()
        if a in last_step:
            p = last_step[a]
            p.next.append(gn)
            gn.previous.append(p)
        last_step[a] = gn
        for me in s.new_messages:
            if me not in when_sent:
                when_sent[me] = gn
        if not gn.previous:
            init_steps.append(gn)

    events: List[Event] = []
    stack = list(init_steps)  # reference reverses then pushes; net: LIFO order
    while stack:
        gn = stack.pop()
        events.append(gn.event)
        for nxt in gn.next:
            nxt.previous.remove(gn)
            if not nxt.previous:
                stack.append(nxt)

    initial = original[0]
    new_trace = [initial]
    prev = initial
    for event in events:
        nxt = prev.step_event(event, None, skip_checks=True)
        if nxt is None:
            LOG.error("Human-readable reorder produced null state; "
                      "returning original trace")
            return original
        if nxt == prev:  # skip no-op events
            continue
        new_trace.append(nxt)
        prev = nxt
    return new_trace


def human_readable_trace_end_state(state: SearchState) -> SearchState:
    return human_readable_trace(state)[-1]


class SerializableTrace:

    def __init__(self, history: List[Event],
                 invariants: List[StatePredicate],
                 generator: NodeGenerator,
                 server_addresses: List[Address],
                 client_workers: List[Tuple[Address, Workload]],
                 lab_id: str, lab_part: Optional[int],
                 test_class_name: str, test_method_name: str):
        self.history = list(history)
        self.invariants = list(invariants)
        self.generator = generator
        self.server_addresses = list(server_addresses)
        self.client_workers = list(client_workers)
        self.lab_id = lab_id
        self.lab_part = lab_part
        self.test_class_name = test_class_name
        self.test_method_name = test_method_name
        self.created_at = time.time()
        self.file_name: Optional[str] = None

    # ------------------------------------------------------------ replaying

    def initial_state(self) -> SearchState:
        state = SearchState(self.generator)
        for a in self.server_addresses:
            state.add_server(a)
        for a, workload in self.client_workers:
            workload.reset()
            state.add_client_worker(a, workload)
        return state

    def end_state(self) -> Optional[SearchState]:
        s = self.initial_state()
        for e in self.history:
            nxt = s.step_event(e, None, skip_checks=True)
            if nxt is None:
                return None
            s = nxt
        return s

    # ----------------------------------------------------------- persistence

    def default_file_name(self) -> str:
        part = f"part{self.lab_part}" if self.lab_part is not None else ""
        stamp = time.strftime("%Y-%m-%d_%H-%M-%S", time.localtime(self.created_at))
        return f"lab{self.lab_id}{part}_{stamp}.trace"

    def save(self, directory: str = TRACES_DIR) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, self.default_file_name())
        n = 1
        while os.path.exists(path):
            path = os.path.join(directory, f"{self.default_file_name()}.{n}")
            n += 1
        with open(path, "wb") as f:
            pickle.dump(self, f)
        self.file_name = path
        return path

    @staticmethod
    def load(path: str) -> Optional["SerializableTrace"]:
        try:
            with open(path, "rb") as f:
                trace = pickle.load(f)
            trace.file_name = path
            return trace
        except Exception as e:  # noqa: BLE001 — stale traces are skipped
            LOG.warning("Skipping unreadable trace %s: %r", path, e)
            return None

    @staticmethod
    def traces(directory: str = TRACES_DIR) -> List["SerializableTrace"]:
        if not os.path.isdir(directory):
            return []
        out = []
        for name in sorted(os.listdir(directory)):
            if ".trace" not in name:
                continue
            t = SerializableTrace.load(os.path.join(directory, name))
            if t is not None:
                out.append(t)
        return out

    def __repr__(self) -> str:
        return (f"SerializableTrace(lab={self.lab_id}, part={self.lab_part}, "
                f"test={self.test_method_name}, events={len(self.history)})")


def save_trace(state: SearchState, invariants: List[StatePredicate],
               lab_id: str, lab_part: Optional[int],
               test_class_name: str, test_method_name: str,
               directory: str = TRACES_DIR) -> str:
    """Persist the trace ending at ``state`` (SearchState.java:490-532)."""
    trace = state.trace()
    history = [s.previous_event for s in trace[1:]]
    end = state
    client_workers = []
    for a, w in end.client_workers().items():
        workload = w.workload
        workload.reset()
        client_workers.append((a, workload))
    st = SerializableTrace(
        history, invariants, end.generator,
        list(end.servers.keys()), client_workers,
        lab_id, lab_part, test_class_name, test_method_name)
    return st.save(directory)
