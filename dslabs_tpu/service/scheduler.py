"""Fairness-preserving job scheduler for the checking service (ISSUE 11).

Three policies compose here, and all three are DETERMINISTIC given the
submission order (the soak's isolation proof depends on that):

* **Per-tenant concurrency quotas.**  A tenant never holds more than
  ``quota`` workers at once, no matter how deep its backlog — one
  tenant's thousand submissions cannot monopolise the mesh.
* **Deficit round-robin (DRR).**  Each eligible tenant accrues
  ``quantum`` credit per rotation; a job runs when its tenant's
  deficit covers its ``budget_units`` cost.  Tenants submitting many
  small jobs and tenants submitting few large ones converge to the
  same budget share — the classic fair-queueing argument, applied to
  search budgets instead of packet bytes.
* **Bounded retry-with-backoff, degraded by failure kind.**  Attempt
  outcomes are classified by the UNIFIED child-death taxonomy
  (supervisor.classify_child_death — the same vocabulary the warden
  and the elastic ladder use), and each kind buys a different, always
  strictly-lighter next attempt:

  - ``oom``    -> a knob-shrink re-level: halve the chunk (the PR 9
    ``classify_oom`` answer, applied at job granularity);
  - ``wedge``  -> a kill + rung-step: drop the burned first rung and
    resume the remaining ladder from the job's checkpoint;
  - ``crash``  -> a plain backoff retry (the environment is suspect,
    the config is not);
  - ``failed`` -> NO retry: the child reported a classified in-child
    failure — retrying a deterministic failure buys nothing, the job
    lands a structured failure verdict instead.

``fairness_index`` is the bench/ledger metric: max over tenants of
verdicts-per-budget divided by the mean (1.0 = perfectly fair; the
ledger compare flags a rise past the threshold — telemetry.py).
"""

from __future__ import annotations

import dataclasses
import os
from collections import deque
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.service.queue import Job

__all__ = ["RetrySpec", "AttemptPlan", "DeficitRoundRobin",
           "degrade", "fairness_index"]


@dataclasses.dataclass(frozen=True)
class RetrySpec:
    """Per-job retry budget (DSLABS_SERVICE_MAX_ATTEMPTS) + backoff."""

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_factor: float = 2.0
    backoff_max: float = 2.0

    def backoff(self, attempt: int) -> float:
        return min(self.backoff_base * (self.backoff_factor ** attempt),
                   self.backoff_max)

    @classmethod
    def from_env(cls) -> "RetrySpec":
        try:
            n = int(os.environ.get("DSLABS_SERVICE_MAX_ATTEMPTS", "")
                    or 3)
        except ValueError:
            n = 3
        return cls(max_attempts=max(1, n))


@dataclasses.dataclass
class AttemptPlan:
    """What the NEXT warden launch for a job looks like after the
    degradation policy has been applied."""

    attempt: int
    chunk: int
    ladder: Tuple[str, ...]
    knob_shrinks: int = 0
    rung_steps: int = 0

    def span_id(self, job_id: str) -> str:
        """This attempt's causal-trace span id (ISSUE 13) — the
        DETERMINISTIC derivation shared with the trace assembler
        (tpu/tracing.py ``attempt_span_id``): the warden passes it to
        children as ``DSLABS_PARENT_SPAN`` and the assembler rebuilds
        it from the journal's ``start`` record alone, so the two link
        without any extra journal field."""
        from dslabs_tpu.tpu.tracing import attempt_span_id

        return attempt_span_id(job_id, self.attempt)


def degrade(plan: AttemptPlan, kind: str,
            retry: RetrySpec) -> Optional[AttemptPlan]:
    """Map a classified death kind to the next attempt plan, or None
    when the job must land a structured failure instead (retry budget
    exhausted, or a reported deterministic failure).  Every retry is
    strictly lighter than the attempt it replaces — the service never
    re-runs a failing config unchanged."""
    if kind == "failed" or plan.attempt >= retry.max_attempts:
        return None
    if kind == "oom":
        return AttemptPlan(plan.attempt + 1, max(1, plan.chunk // 2),
                           plan.ladder, plan.knob_shrinks + 1,
                           plan.rung_steps)
    if kind == "wedge":
        ladder = plan.ladder[1:] if len(plan.ladder) > 1 else ("host",)
        return AttemptPlan(plan.attempt + 1, plan.chunk, ladder,
                           plan.knob_shrinks, plan.rung_steps + 1)
    # crash (and anything unrecognised): plain bounded retry.
    return AttemptPlan(plan.attempt + 1, plan.chunk, plan.ladder,
                       plan.knob_shrinks, plan.rung_steps)


class DeficitRoundRobin:
    """The DRR pick loop.  ``push`` keeps per-tenant FIFOs in tenant
    arrival order; ``pick`` returns the next runnable job honoring the
    concurrency quotas, or None when nothing is eligible right now
    (quota-blocked or empty)."""

    def __init__(self, quantum: float = 1.0, quota: int = 1,
                 quotas: Optional[Dict[str, int]] = None):
        self.quantum = float(quantum)
        self.default_quota = max(1, int(quota))
        self.quotas = dict(quotas or {})
        self._queues: Dict[str, "deque[Job]"] = {}
        self._deficit: Dict[str, float] = {}
        self._order: List[str] = []      # tenant rotation, arrival order
        self._rr = 0

    def quota_for(self, tenant: str) -> int:
        return int(self.quotas.get(tenant, self.default_quota))

    def push(self, job: Job) -> None:
        q = self._queues.get(job.tenant)
        if q is None:
            q = self._queues[job.tenant] = deque()
            self._deficit.setdefault(job.tenant, 0.0)
            self._order.append(job.tenant)
        q.append(job)

    def pending(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def pending_by_tenant(self) -> Dict[str, int]:
        return {t: len(q) for t, q in self._queues.items() if q}

    def pick(self, running: Dict[str, int]) -> Optional[Job]:
        """One DRR rotation: among tenants with pending work AND free
        quota, serve the first (in rotating order) whose deficit covers
        its head job's cost; if none can afford theirs yet, top every
        eligible tenant up by ``quantum`` and try again.  Bounded: the
        costliest head job caps the number of top-ups."""
        eligible = [t for t in self._order
                    if self._queues.get(t)
                    and running.get(t, 0) < self.quota_for(t)]
        if not eligible:
            return None
        max_cost = max(max(j.budget_units for j in self._queues[t])
                       for t in eligible)
        rounds = int(max_cost / self.quantum) + 2
        for _ in range(max(rounds, 2)):
            n = len(self._order)
            for k in range(n):
                t = self._order[(self._rr + k) % n]
                if t not in eligible:
                    continue
                job = self._queues[t][0]
                if self._deficit[t] >= job.budget_units:
                    self._queues[t].popleft()
                    self._deficit[t] -= job.budget_units
                    if not self._queues[t]:
                        # An idle tenant must not bank credit — that is
                        # DRR's no-free-lunch rule (deficit carries only
                        # while backlogged).
                        self._deficit[t] = 0.0
                    self._rr = (self._rr + k + 1) % n
                    return job
            for t in eligible:
                self._deficit[t] += self.quantum
        return None

    def pick_batch(self, running: Dict[str, int], signature_of,
                   max_jobs: int) -> List[Job]:
        """One LANE-BATCH pick (ISSUE 14, tpu/lanes.py): the normal
        DRR pick seeds the batch, then further picks join only when
        ``signature_of`` matches the seed's lane signature — quota and
        deficit semantics are EXACTLY the solo pick's (each joining
        job is a real DRR pick against the tentative running counts,
        so a tenant's lane count obeys its quota and its deficit is
        charged per job).  Non-matching picks are restored to the
        FRONT of their tenant queues with their deficit refunded —
        the batch fill never reorders or starves a neighbor."""
        job = self.pick(running)
        if job is None:
            return []
        batch = [job]
        sig = signature_of(job)
        if sig is None or max_jobs <= 1:
            return batch
        run2 = dict(running)
        run2[job.tenant] = run2.get(job.tenant, 0) + 1
        skipped: List[Job] = []
        while len(batch) < max_jobs and len(skipped) < 2 * max_jobs:
            nxt = self.pick(run2)
            if nxt is None:
                break
            if signature_of(nxt) == sig:
                batch.append(nxt)
                run2[nxt.tenant] = run2.get(nxt.tenant, 0) + 1
            else:
                skipped.append(nxt)
        for j in reversed(skipped):
            q = self._queues.get(j.tenant)
            if q is None:
                q = self._queues[j.tenant] = deque()
                self._deficit.setdefault(j.tenant, 0.0)
                self._order.append(j.tenant)
            q.appendleft(j)
            self._deficit[j.tenant] = (self._deficit.get(j.tenant, 0.0)
                                       + j.budget_units)
        return batch


def fairness_index(per_tenant: Dict[str, dict]) -> float:
    """max/mean of per-tenant verdicts-per-budget — the metric the
    bench's ``service`` phase reports and ``telemetry compare`` tracks.
    1.0 = perfectly fair; a rising index means some tenant converts
    budget into verdicts disproportionately (a starved neighbor).
    Tenants that spent no budget are excluded; no data = 1.0."""
    rates = []
    for stats in per_tenant.values():
        budget = float(stats.get("budget_spent", 0.0) or 0.0)
        if budget <= 0:
            continue
        rates.append(float(stats.get("verdicts", 0)) / budget)
    if not rates or max(rates) <= 0:
        return 1.0
    mean = sum(rates) / len(rates)
    return round(max(rates) / mean, 4) if mean > 0 else 1.0
