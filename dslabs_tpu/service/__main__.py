"""Service CLI: ``python -m dslabs_tpu.service {submit,status,drain}``.

The queue journal is the hand-off: ``submit`` appends durably and
returns (the structured accept/reject line on stdout), a later
``drain`` — on the same ``--root`` — replays the journal and runs the
backlog under the scheduler, and ``status`` renders SERVER_STATUS.json
plus the journal summary without touching either.  Every subcommand
prints exactly one JSON line on stdout (stderr is free-form), so the
CLI composes with scripts the same way bench.py does.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

__all__ = ["main"]


def _parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m dslabs_tpu.service",
        description="multi-tenant checking service: submit jobs, "
                    "inspect status, drain the queue (docs/service.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("submit", help="enqueue one job (structured "
                       "accept/reject on stdout; never blocks)")
    s.add_argument("--root", required=True,
                   help="service run dir (journal + job run dirs)")
    s.add_argument("--tenant", default="default")
    s.add_argument("--factory", required=True,
                   help="'module:callable' protocol factory spec")
    s.add_argument("--kwargs", default="{}",
                   help="factory kwargs as a JSON object")
    s.add_argument("--transform", default=None,
                   help="optional 'module:callable' protocol transform")
    s.add_argument("--max-depth", type=int, default=None)
    s.add_argument("--max-secs", type=float, default=None)
    s.add_argument("--budget", type=float, default=1.0,
                   help="DRR budget units this job is billed")
    s.add_argument("--chunk", type=int, default=1 << 10)
    s.add_argument("--no-admission", action="store_true",
                   help="skip the conformance admission gate")

    st = sub.add_parser("status", help="render SERVER_STATUS.json + "
                        "the journal summary")
    st.add_argument("--root", required=True)

    d = sub.add_parser("drain", help="run the journaled backlog to "
                       "completion under the fair scheduler")
    d.add_argument("--root", required=True)
    d.add_argument("--workers", type=int, default=None)
    d.add_argument("--max-secs", type=float, default=None)
    d.add_argument("--no-admission", action="store_true")
    d.add_argument("--lanes", type=int, default=None,
                   help="batched job lanes: pack up to N compatible "
                        "jobs into one compiled program "
                        "(DSLABS_LANES; 0/1 = off)")
    d.add_argument("--full", action="store_true",
                   help="include per-job results in the JSON line")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)

    from dslabs_tpu.service.server import CheckServer

    if args.cmd == "submit":
        srv = CheckServer(args.root,
                          admission=not args.no_admission)
        try:
            res = srv.submit(
                factory=args.factory, tenant=args.tenant,
                factory_kwargs=json.loads(args.kwargs),
                transform=args.transform, max_depth=args.max_depth,
                max_secs=args.max_secs, budget_units=args.budget,
                chunk=args.chunk)
        finally:
            srv.close()
        print(json.dumps(res))
        return 0 if res.get("accepted") else 1

    if args.cmd == "status":
        from dslabs_tpu.service.queue import ServiceQueue
        from dslabs_tpu.tpu import tracing
        import os

        from dslabs_tpu.service.server import SERVER_STATUS_NAME

        # Both snapshots are read TOLERANTLY (ISSUE 13 satellite): a
        # mid-write SERVER_STATUS (the tmp+replace race) or a torn
        # COSTS.jsonl tail (a server killed mid-append) must degrade
        # to partial output, never a crashed status command.
        status_path = os.path.join(args.root, SERVER_STATUS_NAME)
        server = tracing.load_json_tolerant(status_path)
        cost_recs, _torn = tracing.read_flight_lax(
            os.path.join(args.root, tracing.COSTS_NAME))
        q = ServiceQueue(args.root)
        try:
            summary = q.summary()
        finally:
            q.close()
        print(json.dumps({"server": server, "queue": summary,
                          "costs": tracing.aggregate_costs(cost_recs),
                          "status_path": status_path}))
        return 0

    # drain
    srv = CheckServer(args.root, workers=args.workers,
                      admission=not args.no_admission,
                      lanes=args.lanes)
    try:
        summary = srv.drain(max_secs=args.max_secs)
    finally:
        srv.close()
    if not args.full:
        summary = dict(summary)
        summary["results"] = [
            {k: r.get(k) for k in ("job_id", "tenant", "trace_id",
                                   "status", "end", "unique",
                                   "attempts", "degraded", "kind")}
            for r in summary.get("results", [])]
    print(json.dumps(summary))
    return 0 if summary.get("failed", 0) == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
