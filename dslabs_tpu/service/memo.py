"""Cross-job memoization for the checking service (ISSUE 16 tentpole).

The service workload is adversarially redundant — a class of a thousand
students submits near-identical protocols, and each student resubmits
after a one-line fix — yet before this module every accepted job
re-explored its state space from the root.  Three reuse legs, all keyed
on a STRUCTURAL spec fingerprint (never source text):

* **Verdict cache** — an exact-key hit (same structure, same predicates,
  same budget and engine-relevant knobs) returns the cached verdict with
  zero device dispatches, journaled as a ``memo_hit`` event with a
  ``cached=true`` verdict and a near-zero COSTS charge.
* **Warm start** — same structure, bigger budget: the new job's run dir
  is pre-seeded with the prior run's deepest checkpoint (device visited
  table + host spill tier + frontier all restore through the existing
  ``tpu/checkpoint.py`` path), so the search resumes at the cached
  frontier depth with EXACT counts — bit-identical to a cold run at
  equal depth, because the checkpoint stores the exact visited union.
* **Incremental re-check** — the structural diff localizes to a handler
  set H: tag-reachability over the compiled spec's event table bounds
  the first level whose expansion could fire H, and the job resumes
  from the deepest archived per-level checkpoint at or below that bound
  (``levels_skipped`` >= 1 for any handler not reachable at the root).

Invalidation is loud and conservative: the engine checkpoint
``config_fingerprint`` (protocol name/widths/caps, strictness, symmetry
perm count, checkpoint format version), the pack/symmetry env gates, and
the memo format version all ride the key; any mismatch — or any spec
whose closure the fingerprinter cannot hash by VALUE — is a cold run,
never a stale verdict.  The known boundary: a tenant module's own file
contents are hashed into the introspection cache key, but modules IT
imports are not — docs/memo.md spells out the contract.

Knobs: ``DSLABS_MEMO`` (service default ON), ``DSLABS_MEMO_DIR``
(default ``<root>/memo``), ``DSLABS_MEMO_TIER_CAP`` (largest visited
tier archived per signature, default 4M keys).

Store layout (beside the service journal, torn-tolerant):

    memo/verdicts.jsonl            append-only exact-key verdict lines
    memo/sigs/<sig>/sig.json       signature record (atomic replace)
    memo/sigs/<sig>/ckpt.npz       deepest checkpoint for the signature
    memo/sigs/<sig>/tier.npz       versioned visited tier (tpu/spill.py)
    memo/sigs/<sig>/levels/*.npz   per-level checkpoints (incremental)

Running this module as ``__main__`` is the CPU-pinned introspection
child (the same parent/child split as the admission gate): it builds
the protocol, computes the structural fingerprint + handler effect
table, and prints one JSON line.
"""

from __future__ import annotations

import ast
import hashlib
import inspect
import json
import os
import shutil
import sys
import textwrap
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["MemoStore", "MemoPlan", "MEMO_FORMAT", "memo_enabled",
           "memo_dir", "introspect_protocol", "introspect_child",
           "factory_source_hash", "env_fingerprint", "key_fields",
           "verdict_key", "sig_key", "divergence_depth",
           "witness_digest", "UNCACHEABLE_ENDS"]

MEMO_FORMAT = "dslabs-memo-v1"

# Verdicts whose end condition depends on wall time or transient
# capacity pressure are never cached — an identical resubmit could
# legitimately produce a different (better) answer.
UNCACHEABLE_ENDS = ("TIME_EXHAUSTED", "CAPACITY_EXHAUSTED")

_FALSY = ("0", "off", "false", "no")


def memo_enabled(env: Optional[dict] = None) -> bool:
    """``DSLABS_MEMO``: ON by default for the service path."""
    e = env if env is not None else os.environ
    return str(e.get("DSLABS_MEMO", "1")).strip().lower() not in _FALSY


def memo_dir(root: str, env: Optional[dict] = None) -> str:
    e = env if env is not None else os.environ
    return e.get("DSLABS_MEMO_DIR") or os.path.join(root, "memo")


def _tier_cap(env: Optional[dict] = None) -> int:
    e = env if env is not None else os.environ
    try:
        return int(e.get("DSLABS_MEMO_TIER_CAP", "") or (1 << 22))
    except ValueError:
        return 1 << 22


def _sha(obj) -> str:
    """Canonical short hash of a JSON-able object."""
    blob = json.dumps(obj, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


# ------------------------------------------------------------ fingerprint
#
# The structural fingerprint hashes WHAT the spec is, not what it is
# called or how it is formatted: node kinds (fields, domains, init),
# message/timer types (fields, bounds), caps, symmetry groups, initial
# events, handler ASTs (docstrings/decorators/function names stripped),
# and predicate ASTs.  The spec's display name, the factory module
# name, whitespace, and comments do NOT participate — a rename-only
# resubmit lands the same fingerprint.


class _HashAcc:
    """Accumulates value hashes; remembers when a closure cell could
    only be hashed by TYPE (not value) — such fingerprints are marked
    weak and the store refuses to memoize on them."""

    def __init__(self):
        self.weak = False


def _fn_ast_hash(fn, acc: _HashAcc) -> str:
    """AST-normalized hash of one handler/predicate: decorators and the
    function name and docstring are stripped so a renamed or re-wrapped
    but behaviorally identical function hashes the same.  Closure cell
    VALUES participate (a spec parameterized by ``workload_size``
    captures it), via :func:`_code_hash`."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fd = tree.body[0]
        if isinstance(fd, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fd.decorator_list = []
            fd.name = "_h"
            body = list(fd.body)
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                body = body[1:] or [ast.Pass()]
            fd.body = body
        dump = ast.dump(tree, include_attributes=False)
    except (OSError, TypeError, SyntaxError, IndentationError,
            ValueError):
        # No retrievable source (REPL, C function, exec'd code): fall
        # back to the bytecode hash, which still normalizes names out.
        return _code_hash(fn, acc)
    cells = _closure_values(fn, acc)
    return _sha({"ast": hashlib.sha256(dump.encode()).hexdigest(),
                 "cells": cells,
                 "defaults": [_value_hash(v, acc)
                              for v in (fn.__defaults__ or ())]})


def _closure_values(fn, acc: _HashAcc) -> list:
    out = []
    for name, cell in zip(fn.__code__.co_freevars,
                          fn.__closure__ or ()):
        try:
            v = cell.cell_contents
        except ValueError:
            out.append([name, "<empty>"])
            continue
        out.append([name, _value_hash(v, acc)])
    return out


def _code_hash(fn, acc: _HashAcc) -> str:
    code = fn.__code__
    consts = [_value_hash(c, acc) for c in code.co_consts]
    return _sha({"co": hashlib.sha256(code.co_code).hexdigest(),
                 "consts": consts, "names": code.co_names,
                 "nargs": code.co_argcount,
                 "cells": _closure_values(fn, acc),
                 "defaults": [_value_hash(v, acc)
                              for v in (fn.__defaults__ or ())]})


def _value_hash(v, acc: _HashAcc, depth: int = 0) -> str:
    """Hash an arbitrary captured value BY VALUE where possible.  The
    escape hatch (type-only) marks the accumulator weak: two different
    specs could then collide, so the store treats a weak fingerprint as
    non-memoizable rather than risk a stale verdict."""
    import numpy as np

    if depth > 6:
        acc.weak = True
        return f"<deep:{type(v).__name__}>"
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return repr(v)
    if isinstance(v, types_code := type((lambda: 0).__code__)):
        return hashlib.sha256(v.co_code).hexdigest()[:16]
    if callable(v) and hasattr(v, "__code__"):
        return _fn_ast_hash(v, acc)
    if isinstance(v, (tuple, list)):
        return _sha([_value_hash(x, acc, depth + 1) for x in v])
    if isinstance(v, dict):
        return _sha(sorted((repr(k), _value_hash(x, acc, depth + 1))
                           for k, x in v.items()))
    if hasattr(v, "__array__"):
        a = np.asarray(v)
        return _sha({"dtype": str(a.dtype), "shape": a.shape,
                     "sha": hashlib.sha256(a.tobytes()).hexdigest()})
    # Spec-shaped object captured by a predicate wrapper: hash it
    # structurally instead of by identity.
    if hasattr(v, "handlers") and hasattr(v, "messages"):
        try:
            return _sha(_spec_base(v))
        except Exception:  # noqa: BLE001 — fall through to the weak path
            pass
    if isinstance(v, type(os)):  # a module: name is its identity
        return f"<module:{v.__name__}>"
    acc.weak = True
    return f"<type:{type(v).__module__}.{type(v).__qualname__}>"


def _recover_spec(proto):
    """A compiled ``ProtocolSpec`` twin carries its spec in the
    ``step_message`` closure — recover it so generated twins fingerprint
    structurally (handler ASTs) instead of through opaque closures."""
    from dslabs_tpu.tpu.compiler import ProtocolSpec

    if isinstance(proto, ProtocolSpec):
        return proto
    fn = getattr(proto, "step_message", None)
    for cell in (getattr(fn, "__closure__", None) or ()):
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, ProtocolSpec):
            return v
    return None


def _field_init(f, acc: _HashAcc):
    return (_fn_ast_hash(f.init, acc) if callable(f.init)
            else repr(f.init))


def _slot_decls(spec, acc: _HashAcc) -> list:
    """Slots declarations, fingerprinted from ``spec.slot_blocks`` —
    the EXPANDED node fields carry each record field's lanes and
    domain, but not the ``clear`` value ``slot_clear_upto`` writes or
    the block's logical base, which are read off the declaration at
    trace time.  A declaration missing the expected shape (a duck-typed
    block from a partially-spec'd protocol) marks the fingerprint weak
    so the store refuses to memoize on it."""
    out = []
    for (kind, _bn), b in sorted(getattr(spec, "slot_blocks", {}).items()):
        try:
            out.append([kind, b.name, b.n, b.base,
                        [[sf.name, _field_init(sf, acc), sf.lo,
                          repr(sf.hi), repr(sf.delta), sf.clear]
                         for sf in b.fields]])
        except AttributeError:
            acc.weak = True
            out.append([kind, repr(type(b))])
    return out


def _quorum_decls(spec, acc: _HashAcc) -> list:
    """Quorum declarations: the threshold participates — ``ctx.quorum``
    reads resolve through it, so "majority" -> 2 is a semantic change
    invisible to handler ASTs."""
    out = []
    for q in getattr(spec, "quorums", ()) or ():
        try:
            out.append([q.name, q.over, repr(q.threshold)])
        except AttributeError:
            acc.weak = True
            out.append([repr(type(q))])
    return sorted(out)


def _spec_base(spec, acc: Optional[_HashAcc] = None) -> dict:
    """The structure of a declarative spec MINUS its handlers and
    display name: kinds, fields+domains, slot blocks, quorums, fragment
    composition, message/timer types, caps, symmetry groups, initial
    events."""
    acc = acc or _HashAcc()
    return {
        "fmt": MEMO_FORMAT, "kind": "spec",
        "nodes": [[k.name, k.count,
                   [[f.name, f.size, _field_init(f, acc), f.lo,
                     repr(f.hi), repr(getattr(f, "index_group", None))]
                    for f in k.fields]] for k in spec.nodes],
        "slots": _slot_decls(spec, acc),
        "quorums": _quorum_decls(spec, acc),
        "fragments": sorted(list(getattr(spec, "fragments", []) or [])),
        "messages": [[m.name, list(m.fields),
                      sorted((k, list(v)) for k, v in
                             (m.bounds or {}).items())]
                     for m in spec.messages],
        "timers": [[t.name, list(t.fields), t.min_ms, t.max_ms,
                    sorted((k, list(v)) for k, v in
                           (t.bounds or {}).items())]
                   for t in spec.timers],
        "net_cap": spec.net_cap, "timer_cap": spec.timer_cap,
        "symmetry": repr(getattr(spec, "symmetry", None)),
        "initial_messages": repr(spec.initial_messages),
        "initial_timers": repr(spec.initial_timers),
    }


def _twin_base(proto, acc: _HashAcc) -> dict:
    """Structural base for a HAND-WRITTEN TensorProtocol twin: the lane
    layout, the concrete initial arrays, and the step closures hashed
    by code + captured values.  The protocol's display name is
    excluded from the MEMO fingerprint (it still rides the checkpoint
    config fingerprint, which guards warm-start seeding)."""
    import numpy as np

    def _arr(fn):
        a = np.asarray(fn())
        return {"dtype": str(a.dtype), "shape": a.shape,
                "sha": hashlib.sha256(a.tobytes()).hexdigest()}

    return {
        "fmt": MEMO_FORMAT, "kind": "twin",
        "n_nodes": proto.n_nodes, "node_width": proto.node_width,
        "msg_width": proto.msg_width, "timer_width": proto.timer_width,
        "net_cap": proto.net_cap, "timer_cap": proto.timer_cap,
        "max_sends": proto.max_sends, "max_sets": proto.max_sets,
        "max_live_sends": getattr(proto, "max_live_sends", None),
        "init_nodes": _arr(proto.init_nodes),
        "init_messages": _arr(proto.init_messages),
        "init_timers": _arr(proto.init_timers),
        "symmetry": repr(getattr(proto, "symmetry", None)),
        "lane_domains": repr(sorted(
            (getattr(proto, "lane_domains", None) or {}).items())),
    }


def _unwrap_pred(fn):
    """The spec compiler wraps each predicate in a ``_pred`` closure —
    hash the tenant's function, not the wrapper, so the same predicate
    attached pre- or post-compile fingerprints identically."""
    code = getattr(fn, "__code__", None)
    if code is not None and "fn" in code.co_freevars:
        idx = code.co_freevars.index("fn")
        try:
            inner = (fn.__closure__ or ())[idx].cell_contents
        except (ValueError, IndexError):
            return fn
        if callable(inner):
            return inner
    return fn


def _proto_predicates(proto, acc: _HashAcc) -> Dict[str, str]:
    preds: Dict[str, str] = {}
    for role in ("goals", "invariants", "prunes"):
        for name, fn in sorted(
                (getattr(proto, role, None) or {}).items()):
            preds[f"{role}:{name}"] = _fn_ast_hash(_unwrap_pred(fn), acc)
    for role in ("deliver_message", "deliver_timer",
                 "deliver_message_rt", "deliver_timer_rt", "msg_dest"):
        fn = getattr(proto, role, None)
        if fn is not None:
            preds[f"mask:{role}"] = _fn_ast_hash(fn, acc)
    return preds


def _handler_effects(spec) -> Dict[str, dict]:
    """The compiled spec's event table: run every handler ONCE with a
    dummy context (the ``_count_budgets`` discipline — handlers are
    straight-line over the combinators) and read the concrete message
    tag (row lane 0) / timer tag (row lane 1) off each effect row.
    Nested ``ctx.cond`` children share the same effect lists, so
    conditional sends are captured too."""
    import jax.numpy as jnp

    from dslabs_tpu.tpu.compiler import Ctx

    table, _ = spec._layout()

    def dummy_state():
        return {key: (jnp.zeros((), jnp.int32) if size == 1
                      else jnp.zeros((size,), jnp.int32))
                for key, (_, size) in table.items()}

    false = jnp.asarray(False)
    eff: Dict[str, dict] = {}
    seen = set()
    for kind, i in spec._instances():
        if kind.name in seen:
            continue
        seen.add(kind.name)
        for m in spec.messages:
            fn = spec.handlers.get((kind.name, m.name))
            if fn is None:
                continue
            sends: list = []
            sets: list = []
            ctx = Ctx(spec, dummy_state(), kind.name, i, false, sends,
                      sets, handler=spec._handler_id(fn))
            spec._invoke(
                fn, ctx,
                {f: jnp.zeros((), jnp.int32) for f in m.fields}
                | {"_from": jnp.zeros((), jnp.int32)}, m.name)
            eff[f"m:{kind.name}:{m.name}"] = {
                "trigger": f"m{spec._mtag[m.name]}",
                "sends": sorted({f"m{int(r[0])}" for r, _ in sends}),
                "sets": sorted({f"t{int(r[1])}" for r, _ in sets})}
        for t in spec.timers:
            fn = spec.timer_handlers.get((kind.name, t.name))
            if fn is None:
                continue
            sends, sets = [], []
            ctx = Ctx(spec, dummy_state(), kind.name, i, false, sends,
                      sets, handler=spec._handler_id(fn))
            spec._invoke(
                fn, ctx,
                {f: jnp.zeros((), jnp.int32) for f in t.fields},
                t.name)
            eff[f"t:{kind.name}:{t.name}"] = {
                "trigger": f"t{spec._ttag[t.name]}",
                "sends": sorted({f"m{int(r[0])}" for r, _ in sends}),
                "sets": sorted({f"t{int(r[1])}" for r, _ in sets})}
    return eff


def _initial_events(spec) -> List[str]:
    ev = sorted({f"m{spec._mtag[name]}"
                 for name, _, _, _ in spec.initial_messages}
                | {f"t{spec._ttag[name]}"
                   for name, _, _ in spec.initial_timers})
    return ev


_INF = 1 << 30


def divergence_depth(effects: Dict[str, dict], initial: List[str],
                     changed: List[str]) -> int:
    """Lower bound on the first search depth whose EXPANSION can fire a
    changed handler: Bellman-Ford over event-type availability.  An
    event type is available at depth 0 if initial, else one past the
    earliest firing of ANY handler (changed or not) that emits it —
    using the UNION effect table of the old and new spec keeps the
    bound a true lower bound for both state spaces, so every level at
    or below it is shared and resumable.  Returns ``_INF`` when no
    changed handler's trigger is reachable at all (the edit is dead
    code for this initial condition)."""
    avail = {ev: 0 for ev in initial}
    for _ in range(len(effects) + len(avail) + 2):
        moved = False
        for e in effects.values():
            d = avail.get(e["trigger"])
            if d is None:
                continue
            for out_ev in list(e["sends"]) + list(e["sets"]):
                if avail.get(out_ev, _INF) > d + 1:
                    avail[out_ev] = d + 1
                    moved = True
        if not moved:
            break
    fires = [avail.get(effects[h]["trigger"], _INF)
             for h in changed if h in effects]
    return min(fires) if fires else _INF


def _union_effects(a: Dict[str, dict],
                   b: Dict[str, dict]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for k in set(a) | set(b):
        ea, eb = a.get(k), b.get(k)
        if ea is None or eb is None:
            e = ea or eb
            out[k] = {"trigger": e["trigger"],
                      "sends": list(e["sends"]), "sets": list(e["sets"])}
            continue
        out[k] = {"trigger": ea["trigger"],
                  "sends": sorted(set(ea["sends"]) | set(eb["sends"])),
                  "sets": sorted(set(ea["sets"]) | set(eb["sets"]))}
    return out


def introspect_protocol(proto, env: Optional[dict] = None) -> dict:
    """The full memo view of one live protocol object: structural
    fingerprint (base + handlers + predicates), handler effect table
    (spec twins only), and the engine checkpoint fingerprints the
    warm-start guard compares (strict and beam, under the pack/symmetry
    env the warden child will actually see)."""
    from dslabs_tpu.tpu import checkpoint as ckpt_mod

    e = env if env is not None else os.environ
    acc = _HashAcc()
    spec = _recover_spec(proto)
    if spec is not None:
        base = _spec_base(spec, acc)
        handlers = {
            f"m:{k}:{m}": _fn_ast_hash(fn, acc)
            for (k, m), fn in sorted(spec.handlers.items())}
        handlers.update({
            f"t:{k}:{t}": _fn_ast_hash(fn, acc)
            for (k, t), fn in sorted(spec.timer_handlers.items())})
        effects = _handler_effects(spec)
        initial = _initial_events(spec)
        kind = "spec"
    else:
        base = _twin_base(proto, acc)
        handlers = {
            "step_message": _fn_ast_hash(proto.step_message, acc),
            "step_timer": _fn_ast_hash(proto.step_timer, acc)}
        effects = None
        initial = None
        kind = "twin"
    predicates = _proto_predicates(proto, acc)
    base_fp = _sha(base)
    spec_fp = _sha({"base": base_fp, "handlers": sorted(handlers.items()),
                    "predicates": sorted(predicates.items())})
    sym = 0
    sym_on = str(e.get("DSLABS_SYMMETRY", "")).strip().lower() in (
        "1", "on", "true", "yes")
    if sym_on and getattr(proto, "symmetry", None) is not None:
        try:
            sym = int(proto.symmetry.n_perms)
        except Exception:  # noqa: BLE001 — symmetry spec may be spec-level
            sym = -1  # unknown: poisons the ckpt_fp match, forcing cold
    ckpt_fp = {
        "strict": ckpt_mod.config_fingerprint(
            proto, True, False, symmetry=max(sym, 0)),
        "beam": ckpt_mod.config_fingerprint(
            proto, False, False, symmetry=max(sym, 0))}
    if sym < 0:
        ckpt_fp = {"strict": "<unknown-symmetry>",
                   "beam": "<unknown-symmetry>"}
    return {"ok": True, "fmt": MEMO_FORMAT, "kind": kind,
            "weak": acc.weak, "name": proto.name,
            "base_fp": base_fp, "spec_fp": spec_fp,
            "handlers": handlers, "predicates": predicates,
            "effects": effects, "initial": initial,
            "ckpt_fp": ckpt_fp, "sym": sym}


# --------------------------------------------------------- source keying
#
# The server caches introspection per (factory ref, kwargs, transform,
# FACTORY MODULE FILE HASH): a student editing the module in place gets
# a fresh introspection child (a fresh interpreter — no stale
# sys.modules), so an edited spec can NEVER ride a stale fingerprint
# into the verdict cache.

def factory_source_hash(factory: str,
                        extra_sys_path: Optional[List[str]] = None
                        ) -> Optional[str]:
    import importlib.util

    mod_name = factory.partition(":")[0]
    old = sys.path[:]
    try:
        sys.path[:0] = list(extra_sys_path or [])
        try:
            spec = importlib.util.find_spec(mod_name)
        except (ImportError, ValueError, AttributeError):
            return None
    finally:
        sys.path[:] = old
    origin = getattr(spec, "origin", None) if spec else None
    if not origin or not os.path.isfile(origin):
        return None
    try:
        with open(origin, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None


def introspect_child(factory: str, factory_kwargs: Optional[dict],
                     transform: Optional[str],
                     extra_sys_path: Optional[List[str]] = None,
                     env: Optional[dict] = None,
                     timeout: Optional[float] = None) -> dict:
    """Run the introspection in a CPU-pinned subprocess (the admission
    child's sandbox discipline: tenant code never runs in the server
    process, and a hung or crashing child is a structured miss — the
    job just runs cold)."""
    import subprocess

    if timeout is None:
        try:
            timeout = float(os.environ.get(
                "DSLABS_MEMO_INTROSPECT_SECS", "") or 120.0)
        except ValueError:
            timeout = 120.0
    child_env = dict(os.environ)
    child_env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = [repo_root] + list(extra_sys_path or [])
    if child_env.get("PYTHONPATH"):
        paths.append(child_env["PYTHONPATH"])
    child_env["PYTHONPATH"] = os.pathsep.join(paths)
    child_env.update(env or {})
    spec = {"factory": factory, "factory_kwargs": factory_kwargs or {},
            "transform": transform}
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "dslabs_tpu.service.memo"],
            input=json.dumps(spec), capture_output=True, text=True,
            env=child_env, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"introspection child exceeded "
                                      f"{timeout:.0f}s"}
    except OSError as e:
        return {"ok": False, "error": f"spawn failed: {e}"}
    if proc.returncode != 0 or not proc.stdout.strip():
        tail = (proc.stderr or "").strip().splitlines()[-1:]
        return {"ok": False,
                "error": f"child rc={proc.returncode} tail={tail}"}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except ValueError:
        return {"ok": False, "error": "unparsable child output"}


def _introspect_main() -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — jax may be absent for pure lint
        pass
    spec = json.load(sys.stdin)
    try:
        from dslabs_tpu.service.server import _resolve

        proto = _resolve(spec["factory"])(**(spec.get("factory_kwargs")
                                             or {}))
        if spec.get("transform"):
            proto = _resolve(spec["transform"])(proto)
        out = introspect_protocol(proto)
    except BaseException as e:  # noqa: BLE001 — a raising factory = no memo
        out = {"ok": False,
               "error": f"{type(e).__name__}: {e}"[:300]}
    sys.stdout.write(json.dumps(out) + "\n")
    sys.stdout.flush()
    return 0


# ------------------------------------------------------------- key schema

def env_fingerprint(env: Optional[dict] = None) -> str:
    """The engine-relevant environment a verdict depends on: the packed
    frontier gate, the symmetry gate, the checkpoint format version,
    and the memo format itself.  Framework upgrades that bump either
    format string invalidate every prior entry — loudly cold, never
    stale."""
    from dslabs_tpu.tpu import checkpoint as ckpt_mod

    e = env if env is not None else os.environ
    packed = str(e.get("DSLABS_PACKED", "1")).strip().lower() \
        not in _FALSY
    sym = str(e.get("DSLABS_SYMMETRY", "")).strip().lower() in (
        "1", "on", "true", "yes")
    return (f"packed={int(packed)},sym={int(sym)},"
            f"ckpt={ckpt_mod.FORMAT_VERSION},memo={MEMO_FORMAT}")


def key_fields(intro: dict, strict: bool, chunk: int,
               frontier_cap: int, visited_cap: int,
               ladder: Tuple[str, ...],
               env: Optional[dict] = None) -> dict:
    """Everything except the depth/time budget: the signature key.  The
    verdict key adds (max_depth, max_secs) on top."""
    return {
        "spec_fp": intro["spec_fp"],
        "strict": bool(strict),
        "chunk": int(chunk),
        "frontier_cap": int(frontier_cap),
        "visited_cap": int(visited_cap),
        "ladder": list(ladder),
        "env_fp": env_fingerprint(env),
        "ckpt_fp": intro["ckpt_fp"]["strict" if strict else "beam"],
    }


def sig_key(fields: dict) -> str:
    return _sha(fields)


def verdict_key(fields: dict, max_depth: Optional[int],
                max_secs: Optional[float]) -> str:
    return _sha({"sig": fields, "max_depth": max_depth,
                 "max_secs": max_secs})


def witness_digest(predicate: Optional[str], violating_state,
                   goal_state, trace) -> Optional[str]:
    """A stable digest of the (minimized) witness attached to a
    verdict, so a cached/incremental verdict can be checked
    bit-identical to its cold run without shipping the full state."""
    import numpy as np

    if (predicate is None and violating_state is None
            and goal_state is None):
        return None

    def _state(s):
        if s is None:
            return None
        return {k: np.asarray(v).tolist() for k, v in s.items()}

    return _sha({"predicate": predicate,
                 "violating": _state(violating_state),
                 "goal": _state(goal_state),
                 "trace": (np.asarray(trace).tolist()
                           if trace is not None else None)})


# ------------------------------------------------------------------ store

class MemoPlan:
    """What the store decided for one submission: ``mode`` is one of
    ``cold`` / ``hit`` / ``warm`` / ``incremental``; warm/incremental
    carry the seed checkpoint to copy into the job's run dir."""

    def __init__(self, mode: str, sig: str, fields: dict,
                 seed_ckpt: Optional[str] = None,
                 seed_depth: int = 0, levels_skipped: int = 0,
                 base_device_secs: float = 0.0, reason: str = "",
                 verdict: Optional[dict] = None):
        self.mode = mode
        self.sig = sig
        self.fields = fields
        self.seed_ckpt = seed_ckpt
        self.seed_depth = seed_depth
        self.levels_skipped = levels_skipped
        self.base_device_secs = base_device_secs
        self.reason = reason
        self.verdict = verdict


class MemoStore:
    """The persistent cross-job memo store.  Torn-tolerant by
    construction: the verdict cache is an append-only JSONL (bad lines
    skipped on read), signature records are atomic tmp+replace, and
    every seed file is guarded by the engine's own checkpoint
    fingerprint check plus the versioned tier CRC — a half-written
    artifact yields a cold run, never a wrong one."""

    def __init__(self, path: str, tier_cap: Optional[int] = None,
                 env: Optional[dict] = None):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.join(self.path, "sigs"), exist_ok=True)
        self.verdicts_path = os.path.join(self.path, "verdicts.jsonl")
        self.tier_cap = (int(tier_cap) if tier_cap is not None
                         else _tier_cap(env))
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "warm_starts": 0, "incremental": 0,
                      "levels_skipped": 0, "device_secs_saved": 0.0,
                      "misses": 0, "stores": 0}

    # ---------------------------------------------------------- stats

    def stats_block(self) -> dict:
        with self._lock:
            st = dict(self.stats)
        st["device_secs_saved"] = round(st["device_secs_saved"], 3)
        lookups = st["hits"] + st["warm_starts"] + st["incremental"] \
            + st["misses"]
        st["hit_rate"] = (round(
            (st["hits"] + st["warm_starts"] + st["incremental"])
            / lookups, 3) if lookups else None)
        st["enabled"] = True
        st["dir"] = self.path
        return st

    def bump(self, counter: str, by=1) -> None:
        with self._lock:
            self.stats[counter] += by

    # -------------------------------------------------------- verdicts

    def _iter_verdicts(self):
        try:
            with open(self.verdicts_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except ValueError:
                        continue  # torn tail line: skip, stay sound
        except OSError:
            return

    def lookup_verdict(self, fields: dict, max_depth: Optional[int],
                       max_secs: Optional[float]) -> Optional[dict]:
        key = verdict_key(fields, max_depth, max_secs)
        found = None
        for rec in self._iter_verdicts():
            if rec.get("key") == key:
                found = rec
        return found

    def record_verdict(self, fields: dict, max_depth: Optional[int],
                       max_secs: Optional[float], verdict: dict,
                       device_secs: float) -> bool:
        if verdict.get("status") != "done":
            return False
        if verdict.get("end") in UNCACHEABLE_ENDS:
            return False
        if verdict.get("degraded") or verdict.get("deaths"):
            return False
        keep = {k: verdict.get(k) for k in (
            "end", "unique", "explored", "depth", "engine",
            "predicate", "witness")}
        rec = {"t": "memo_verdict",
               "key": verdict_key(fields, max_depth, max_secs),
               "sig": sig_key(fields), "fields": fields,
               "max_depth": max_depth, "max_secs": max_secs,
               "verdict": keep,
               "device_secs": round(float(device_secs), 4)}
        line = json.dumps(rec) + "\n"
        with self._lock:
            try:
                with open(self.verdicts_path, "a") as f:
                    f.write(line)
            except OSError:
                return False
            self.stats["stores"] += 1
        return True

    # ------------------------------------------------------ signatures

    def sig_dir(self, sig: str) -> str:
        return os.path.join(self.path, "sigs", sig)

    def _load_sig(self, sig: str) -> Optional[dict]:
        try:
            with open(os.path.join(self.sig_dir(sig), "sig.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _sig_levels(self, sig: str) -> Dict[int, str]:
        d = os.path.join(self.sig_dir(sig), "levels")
        out: Dict[int, str] = {}
        try:
            names = os.listdir(d)
        except OSError:
            return out
        for n in names:
            if n.startswith("level_") and n.endswith(".npz"):
                try:
                    out[int(n[len("level_"):-len(".npz")])] = \
                        os.path.join(d, n)
                except ValueError:
                    continue
        return out

    def _tier_ok(self, sig: str, rec: dict) -> Tuple[bool, str]:
        """Validate the signature's archived visited tier (versioned
        format, tpu/spill.py): a CRC/meta refusal means the seed
        lineage is suspect, so the plan falls back to cold — loudly."""
        tier_path = os.path.join(self.sig_dir(sig), "tier.npz")
        if not os.path.exists(tier_path):
            return True, ""  # tier is optional (cap-skipped archives)
        from dslabs_tpu.tpu import spill as spill_mod

        try:
            spill_mod.load_tier(tier_path, expect_meta={
                "pack": rec.get("pack", "identity"),
                "sym": rec.get("sym", 0)})
        except (spill_mod.TierMismatch, spill_mod.TierCorrupt) as e:
            return False, f"{type(e).__name__}: {e}"[:200]
        except Exception as e:  # noqa: BLE001 — any doubt = cold run
            return False, f"{type(e).__name__}: {e}"[:200]
        return True, ""

    # ------------------------------------------------------------ plan

    def plan(self, intro: dict, strict: bool, chunk: int,
             frontier_cap: int, visited_cap: int,
             ladder: Tuple[str, ...],
             max_depth: Optional[int], max_secs: Optional[float],
             env: Optional[dict] = None) -> MemoPlan:
        """Decide the reuse mode for one submission.  Precedence:
        exact verdict hit > warm start (same signature, new budget) >
        incremental (handler-localized diff) > cold.  Every guard
        failure degrades toward cold with a reason string the server
        journals — never an exception, never a stale seed."""
        fields = key_fields(intro, strict, chunk, frontier_cap,
                            visited_cap, ladder, env)
        sig = sig_key(fields)
        if intro.get("weak"):
            return MemoPlan("cold", sig, fields,
                            reason="weak_fingerprint")
        hit = self.lookup_verdict(fields, max_depth, max_secs)
        if hit is not None:
            return MemoPlan(
                "hit", sig, fields,
                base_device_secs=float(hit.get("device_secs", 0.0)),
                verdict=dict(hit.get("verdict") or {}))
        rec = self._load_sig(sig)
        if rec is not None:
            plan = self._plan_same_sig(sig, rec, fields, max_depth)
            if plan is not None:
                return plan
        plan = self._plan_incremental(intro, fields, sig, max_depth)
        if plan is not None:
            return plan
        return MemoPlan("cold", sig, fields, reason="miss")

    def _plan_same_sig(self, sig: str, rec: dict, fields: dict,
                       max_depth: Optional[int]) -> Optional[MemoPlan]:
        if rec.get("ckpt_fp") != fields["ckpt_fp"]:
            return MemoPlan("cold", sig, fields,
                            reason="ckpt_fingerprint_mismatch")
        ok, why = self._tier_ok(sig, rec)
        if not ok:
            return MemoPlan("cold", sig, fields,
                            reason=f"tier_refused:{why}")
        depth = int(rec.get("depth", 0))
        ck = os.path.join(self.sig_dir(sig), "ckpt.npz")
        if os.path.exists(ck) and depth > 0 and (
                max_depth is None or depth <= max_depth):
            return MemoPlan("warm", sig, fields, seed_ckpt=ck,
                            seed_depth=depth, levels_skipped=depth,
                            base_device_secs=float(
                                rec.get("device_secs", 0.0)))
        # Deepest checkpoint overshoots the new (smaller) depth budget:
        # fall back to the deepest archived LEVEL inside it.
        levels = self._sig_levels(sig)
        usable = [d for d in levels
                  if d > 0 and (max_depth is None or d <= max_depth)]
        if usable:
            d = max(usable)
            return MemoPlan("warm", sig, fields, seed_ckpt=levels[d],
                            seed_depth=d, levels_skipped=d,
                            base_device_secs=float(
                                rec.get("device_secs", 0.0)))
        return None

    def _plan_incremental(self, intro: dict, fields: dict,
                          new_sig: str, max_depth: Optional[int]
                          ) -> Optional[MemoPlan]:
        if intro.get("kind") != "spec" or not intro.get("effects"):
            return None
        try:
            sigs = os.listdir(os.path.join(self.path, "sigs"))
        except OSError:
            return None
        for sig in sorted(sigs)[:256]:
            if sig == new_sig:
                continue
            rec = self._load_sig(sig)
            if rec is None:
                continue
            f_old = rec.get("fields") or {}
            if any(f_old.get(k) != fields[k] for k in (
                    "strict", "chunk", "frontier_cap", "visited_cap",
                    "ladder", "env_fp", "ckpt_fp")):
                continue
            if rec.get("base_fp") != intro["base_fp"]:
                continue
            if rec.get("predicates") != intro["predicates"]:
                continue
            old_h = rec.get("handlers") or {}
            new_h = intro["handlers"]
            if set(old_h) != set(new_h):
                continue  # handler added/removed: structure changed
            changed = sorted(k for k in new_h if old_h[k] != new_h[k])
            if not changed:
                continue  # same spec_fp would have matched _plan_same_sig
            ok, why = self._tier_ok(sig, rec)
            if not ok:
                return MemoPlan("cold", new_sig, fields,
                                reason=f"tier_refused:{why}")
            union = _union_effects(rec.get("effects") or {},
                                   intro["effects"])
            e_low = divergence_depth(
                union, intro.get("initial") or [], changed)
            levels = self._sig_levels(sig)
            usable = [d for d in levels
                      if 0 < d <= e_low
                      and (max_depth is None or d <= max_depth)]
            if not usable:
                continue
            d = max(usable)
            return MemoPlan(
                "incremental", new_sig, fields, seed_ckpt=levels[d],
                seed_depth=d, levels_skipped=d,
                base_device_secs=float(rec.get("device_secs", 0.0)),
                reason=f"changed={','.join(changed)[:120]} "
                       f"divergence>={e_low}")
        return None

    # --------------------------------------------------------- archive

    def archive(self, intro: dict, fields: dict, verdict: dict,
                run_dir: str, device_secs: float) -> Optional[str]:
        """Persist one finished cold/warm run for future reuse: the
        deepest checkpoint, the per-level dumps the warden child
        archived (``DSLABS_MEMO_LEVELS``), the versioned visited tier,
        and the signature record — all atomic, never fatal."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        if verdict.get("status") != "done" or verdict.get("deaths"):
            return None
        if intro.get("weak"):
            return None
        src = os.path.join(run_dir, "ckpt.npz")
        if not os.path.exists(src):
            return None
        try:
            fp = ckpt_mod.peek_fingerprint(src)
            depth = ckpt_mod.peek_depth(src)
        except Exception:  # noqa: BLE001 — unreadable dump: skip archive
            return None
        if fp != fields["ckpt_fp"]:
            return None  # foreign dump (e.g. env drifted): never seed it
        sig = sig_key(fields)
        sd = self.sig_dir(sig)
        os.makedirs(os.path.join(sd, "levels"), exist_ok=True)
        old = self._load_sig(sig)
        if old is not None and int(old.get("depth", 0)) >= int(depth):
            self._merge_levels(sig, run_dir)
            return sig  # keep the deeper archive, still adopt levels
        try:
            tmp = os.path.join(sd, "ckpt.npz.tmp")
            shutil.copyfile(src, tmp)
            os.replace(tmp, os.path.join(sd, "ckpt.npz"))
        except OSError:
            return None
        self._merge_levels(sig, run_dir)
        pack, sym, n_keys = self._archive_tier(sd, src, fp)
        rec = {"fmt": MEMO_FORMAT, "sig": sig, "fields": fields,
               "spec_fp": intro["spec_fp"], "base_fp": intro["base_fp"],
               "handlers": intro["handlers"],
               "predicates": intro["predicates"],
               "effects": intro.get("effects"),
               "initial": intro.get("initial"),
               "kind": intro.get("kind"), "name": intro.get("name"),
               "ckpt_fp": fp, "depth": int(depth),
               "pack": pack, "sym": sym, "tier_keys": n_keys,
               "device_secs": round(float(device_secs), 4),
               "end": verdict.get("end")}
        try:
            tmp = os.path.join(sd, "sig.json.tmp")
            with open(tmp, "w") as f:
                f.write(json.dumps(rec))
            os.replace(tmp, os.path.join(sd, "sig.json"))
        except OSError:
            return None
        with self._lock:
            self.stats["stores"] += 1
        return sig

    def _merge_levels(self, sig: str, run_dir: str) -> None:
        src_dir = os.path.join(run_dir, "levels")
        dst_dir = os.path.join(self.sig_dir(sig), "levels")
        try:
            names = os.listdir(src_dir)
        except OSError:
            return
        os.makedirs(dst_dir, exist_ok=True)
        for n in names:
            if not (n.startswith("level_") and n.endswith(".npz")):
                continue
            try:
                tmp = os.path.join(dst_dir, n + ".tmp")
                shutil.copyfile(os.path.join(src_dir, n), tmp)
                os.replace(tmp, os.path.join(dst_dir, n))
            except OSError:
                continue

    def _archive_tier(self, sig_dir: str, ckpt_path: str,
                      fp: str) -> Tuple[str, int, int]:
        """Write the signature's exact visited tier in the versioned
        on-disk format (tpu/spill.py ``save_tier``): the (h1, h2)
        fingerprint union from the checkpoint's ``visited_keys``, with
        the pack descriptor + symmetry flag pinned in the meta so a
        foreign consumer is refused loudly.  Skipped (not truncated!)
        past ``DSLABS_MEMO_TIER_CAP``."""
        import numpy as np

        from dslabs_tpu.tpu import checkpoint as ckpt_mod
        from dslabs_tpu.tpu import spill as spill_mod

        pack, sym = "identity", 0
        try:
            ck = ckpt_mod.load(ckpt_path, fp)
        except Exception:  # noqa: BLE001 — tier is an optional artifact
            return pack, sym, 0
        if ck is None:
            return pack, sym, 0
        if ck.extra and "frontier_encoding" in ck.extra:
            try:
                pack = np.asarray(
                    ck.extra["frontier_encoding"]).tobytes().decode()
            except Exception:  # noqa: BLE001
                pack = "unknown"
        if "sym" in fp:
            # config_fingerprint appends 'symN' for reduced dumps.
            try:
                sym = int(fp.rsplit("sym", 1)[-1].rstrip("'\") ,"))
            except ValueError:
                sym = 1
        keys = np.asarray(ck.visited_keys, np.uint32)
        n = int(keys.shape[0])
        if n > self.tier_cap:
            return pack, sym, 0
        h1, h2 = spill_mod._rows_to_u64(keys)
        try:
            spill_mod.save_tier(
                os.path.join(sig_dir, "tier.npz"), h1, h2,
                meta={"pack": pack, "sym": sym, "ckpt_fp": fp})
        except OSError:
            return pack, sym, 0
        return pack, sym, n


if __name__ == "__main__":
    sys.exit(_introspect_main())
