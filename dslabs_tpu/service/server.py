"""Checking-as-a-service: the resident multi-tenant search server
(ISSUE 11 tentpole).

The composition layer ROADMAP #2 asked for: every prerequisite landed
in earlier PRs and this module only WIRES them —

* **Admission gate** (PR 10): an untrusted (factory spec, predicate)
  submission is linted by ``analysis.conformance`` in a **CPU-pinned
  subprocess** (the spec's own code runs there, never in the server,
  and never near the accelerator) BEFORE any twin is compiled.
  Unsound protocols are rejected with structured ``SpecError``-derived
  verdicts (rule code + location + message); a hung or crashing
  admission child is itself a rejection, never a server stall.
* **One fault domain per job** (PR 4): accepted jobs run as warden
  children with their own run dir
  (``<root>/jobs/<job_id>/`` — checkpoint, flight.jsonl, STATUS.json,
  compile_cache: tpu/checkpoint.py ``run_dir_layout``), heartbeat-
  reaped, so one tenant's OOM/hang/crash is a SIGKILL + classified
  death in ITS domain — a neighbor's verdict stays bit-exact (proven
  by the chaos soak in tests/test_service.py).
* **Fairness-preserving degradation** (PR 9 + service/scheduler.py):
  deaths classify through the unified taxonomy and buy strictly
  lighter retries (oom -> knob-shrink re-level, wedge -> rung-step),
  resumed from the job's durable checkpoint; a reported deterministic
  failure lands a structured failure verdict — never a silent partial
  one, and never an unbounded retry loop burning the queue.
* **Bounded backpressure** (service/queue.py): a full queue answers
  submission with a structured retry-after rejection instead of
  blocking the front end.

``SERVER_STATUS.json`` (atomic tmp+replace, same discipline as the
per-run STATUS.json) aggregates what ``telemetry watch`` shows per
job: queue depth/cap, backpressure state, per-tenant
pending/running/completed/failed/rejected, and the live fairness
index.  Knobs: the ``DSLABS_SERVICE_*`` table in docs/service.md.

CLI: ``python -m dslabs_tpu.service {submit,status,drain}``
(service/__main__.py).  Running THIS module as ``__main__`` is the
admission child half, mirroring tpu/warden.py's parent/child split.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.service import memo as memo_mod
from dslabs_tpu.service.queue import Job, ServiceQueue
from dslabs_tpu.service.scheduler import (AttemptPlan, DeficitRoundRobin,
                                          RetrySpec, degrade,
                                          fairness_index)
from dslabs_tpu.tpu import tracing

__all__ = ["CheckServer", "SERVER_STATUS_NAME", "admission_check"]

SERVER_STATUS_NAME = "SERVER_STATUS.json"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _zero_stats() -> dict:
    return {"submitted": 0, "completed": 0, "failed": 0, "rejected": 0,
            "verdicts": 0, "budget_spent": 0.0}


# -------------------------------------------------------------- admission

def admission_check(factory: str, factory_kwargs: Optional[dict],
                    transform: Optional[str],
                    extra_sys_path: Optional[List[str]] = None,
                    env: Optional[dict] = None,
                    timeout: Optional[float] = None) -> List[dict]:
    """Run the conformance gate over one factory spec in a CPU-pinned
    subprocess.  Returns the finding dicts (``analysis.core.Finding``
    shape, waivers applied); an empty list means admissible.  A child
    that hangs past ``timeout`` (DSLABS_SERVICE_ADMIT_SECS, default
    120) or dies abruptly IS a finding — a hostile spec must not be
    able to wedge or crash its way past the gate."""
    if timeout is None:
        timeout = _env_float("DSLABS_SERVICE_ADMIT_SECS", 120.0)
    child_env = dict(os.environ)
    child_env["JAX_PLATFORMS"] = "cpu"
    paths = [_REPO_ROOT] + list(extra_sys_path or [])
    if child_env.get("PYTHONPATH"):
        paths.append(child_env["PYTHONPATH"])
    child_env["PYTHONPATH"] = os.pathsep.join(paths)
    child_env.update(env or {})
    spec = {"factory": factory, "factory_kwargs": factory_kwargs or {},
            "transform": transform}

    def _gate_error(message: str) -> List[dict]:
        return [{"code": "C4", "leg": "conformance", "path": factory,
                 "obj": "<admission>", "line": 0, "waived": False,
                 "waiver": "", "message": message}]

    try:
        proc = subprocess.run(
            [sys.executable, "-m", "dslabs_tpu.service.server"],
            input=json.dumps(spec), capture_output=True, text=True,
            env=child_env, timeout=timeout)
    except subprocess.TimeoutExpired:
        return _gate_error(
            f"admission child exceeded {timeout:.0f}s (hung import or "
            "hostile spec); rejected")
    except OSError as e:
        return _gate_error(f"admission child failed to spawn: {e}")
    if proc.returncode != 0 or not proc.stdout.strip():
        tail = (proc.stderr or "").strip().splitlines()[-1:][:1]
        return _gate_error(
            f"admission child died rc={proc.returncode} "
            f"(stderr tail: {tail}); rejected")
    try:
        return json.loads(
            proc.stdout.strip().splitlines()[-1]).get("findings", [])
    except ValueError:
        return _gate_error("admission child produced unparsable output")


# ------------------------------------------------------------------ server

class CheckServer:
    """The resident server: bounded persistent queue + admission gate
    + DRR scheduler + per-job warden fault domains.  Thread-safe;
    ``drain`` runs the backlog on ``workers`` worker threads (each job
    is its own child process tree, so workers only pay coordination).
    """

    def __init__(self, root: str,
                 queue_cap: Optional[int] = None,
                 quota: Optional[int] = None,
                 quotas: Optional[Dict[str, int]] = None,
                 workers: Optional[int] = None,
                 admission: Optional[bool] = None,
                 retry: Optional[RetrySpec] = None,
                 warden_kwargs: Optional[dict] = None,
                 env: Optional[dict] = None,
                 extra_sys_path: Optional[List[str]] = None,
                 elastic: bool = True,
                 keep: Optional[int] = None,
                 lanes: Optional[int] = None,
                 lane_swap: Optional[bool] = None,
                 telemetry=None,
                 memo: Optional[bool] = None,
                 memo_path: Optional[str] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.queue = ServiceQueue(self.root, cap=queue_cap)
        # Per-tenant cost ledger (ISSUE 13, tpu/tracing.py): every
        # finished job appends one COSTS.jsonl record built from its
        # verdict counters + its run dir's flight log — zero added
        # device work; a restarted server replays the ledger.
        self.costs = tracing.CostMeter(
            os.path.join(self.root, tracing.COSTS_NAME))
        # Run-dir retention (ISSUE 13 satellite): service roots used to
        # grow without bound — at scheduler idle the oldest FINISHED
        # jobs' run dirs are pruned down to `keep` (DSLABS_SERVICE_KEEP,
        # default 64); running/queued jobs are never touched.
        self.keep = (keep if keep is not None
                     else _env_int("DSLABS_SERVICE_KEEP", 64))
        # Optional parent-side telemetry recorder: retention prunes and
        # scheduler-level events become flight-log events when one is
        # attached (the bench's service phase does).
        self.telemetry = telemetry
        self.workers = (workers if workers is not None
                        else _env_int("DSLABS_SERVICE_WORKERS", 2))
        if admission is None:
            admission = os.environ.get(
                "DSLABS_SERVICE_ADMISSION", "1").strip().lower() not in (
                    "0", "off", "false", "no")
        self.admission = bool(admission)
        self.retry = retry or RetrySpec.from_env()
        self.warden_kwargs = dict(warden_kwargs or {})
        self.env = dict(env or {})
        self.extra_sys_path = list(extra_sys_path or [])
        self.elastic = bool(elastic)
        self.sched = DeficitRoundRobin(
            quota=(quota if quota is not None
                   else _env_int("DSLABS_SERVICE_QUOTA", 1)),
            quotas=quotas)
        # Batched job lanes (ISSUE 14, tpu/lanes.py): with lanes >= 2
        # the scheduler packs compatible queued jobs (same lane
        # signature, quotas preserved) into ONE lane-batch child — N
        # searches advanced by one compiled program, dispatch cost
        # amortised across tenants.  Default OFF (DSLABS_LANES=0): the
        # solo path stays byte-identical for existing callers.
        from dslabs_tpu.tpu import lanes as lanes_mod

        self.lanes = (int(lanes) if lanes is not None
                      else lanes_mod.lanes_enabled())
        self.lane_swap = (bool(lane_swap) if lane_swap is not None
                          else lanes_mod.lane_swap_enabled())
        self.lane_stats = {
            "batches": 0, "jobs": 0, "swaps": 0, "evicted": 0,
            "occupancy_sum": 0.0, "by_signature": {}}
        self._lane_seq = 0
        # Cross-job memoization (ISSUE 16, service/memo.py): ON by
        # default in the service path (DSLABS_MEMO) — an identical
        # resubmit returns its cached verdict with zero device
        # dispatches, a budget-grown resubmit warm-starts from the
        # signature's deepest checkpoint, and a one-handler edit
        # re-checks incrementally from its divergence bound.  OFF
        # leaves every existing path byte-identical (no memo dir, no
        # memo events, no introspection children).
        if memo is None:
            memo = memo_mod.memo_enabled()
        self.memo: Optional[memo_mod.MemoStore] = None
        if memo:
            self.memo = memo_mod.MemoStore(
                memo_path or memo_mod.memo_dir(self.root))
        self._intro_cache: Dict[tuple, dict] = {}
        self.status_path = os.path.join(self.root, SERVER_STATUS_NAME)
        self._lock = threading.Lock()
        self._running: Dict[str, int] = {}
        self._active = 0
        self.stats: Dict[str, dict] = {}
        self._admission_cache: Dict[tuple, List[dict]] = {}
        self.results: List[dict] = []
        # Crash recovery: the queue replays its journal on open; every
        # still-pending job re-enters the scheduler (and will resume
        # its own run-dir checkpoint when it runs).
        for job in list(self.queue.pending):
            self.sched.push(job)
            self.stats.setdefault(job.tenant, _zero_stats())
        self._write_status()

    # ------------------------------------------------------------- submit

    def submit(self, factory: str, tenant: str = "default",
               factory_kwargs: Optional[dict] = None,
               transform: Optional[str] = None,
               strict: bool = True,
               max_depth: Optional[int] = None,
               max_secs: Optional[float] = None,
               budget_units: float = 1.0,
               chunk: int = 1 << 10,
               frontier_cap: int = 1 << 14,
               visited_cap: int = 1 << 20,
               ladder: Tuple[str, ...] = ("device", "host"),
               fault: Optional[dict] = None) -> dict:
        """The submission protocol (docs/service.md).  Returns one of
        three STRUCTURED results — never raises, never blocks:

        * ``{"accepted": True, "job_id", "queue_depth"}``
        * ``{"accepted": False, "reason": "unsound_spec",
          "findings": […]}``  (admission gate, before any compile)
        * ``{"accepted": False, "reason": "queue_full",
          "retry_after_secs", "queue_depth", "queue_cap"}``
        """
        with self._lock:
            st = self.stats.setdefault(tenant, _zero_stats())
        # One trace id per submission (ISSUE 13): minted HERE — the
        # journal persists it on the job record, every phase of the
        # job's life (admission, queue wait, each warden attempt,
        # every child's flight log) is stamped with it, and
        # `telemetry trace` reassembles the causal tree from disk.
        trace_id = tracing.mint_trace_id()
        # Memo introspection (ISSUE 16): runs FIRST so the admission
        # cache can key on the structural fingerprint (satellite:
        # admission and memoization must never disagree about spec
        # identity).  Same sandbox discipline as admission — a
        # CPU-pinned child builds the protocol; a failed introspection
        # is journaled and the job simply runs cold.
        intro = self._introspect(factory, factory_kwargs, transform)
        spec_fp = (intro or {}).get("spec_fp") \
            if (intro or {}).get("ok") else None
        if self.admission:
            t_adm = time.time()
            findings, cached = self._admit(factory, factory_kwargs,
                                           transform, fp=spec_fp)
            unwaived = [f for f in findings if not f.get("waived")]
            self.queue.log_event(
                "admission", tenant=tenant, factory=factory,
                trace_id=trace_id, secs=round(time.time() - t_adm, 3),
                cached=cached, findings=len(unwaived))
            if unwaived:
                self.queue.mark_rejected(
                    tenant, "unsound_spec",
                    {"factory": factory, "trace_id": trace_id,
                     "findings": unwaived[:8]})
                with self._lock:
                    st["rejected"] += 1
                self._write_status()
                return {"accepted": False, "rejected": True,
                        "reason": "unsound_spec", "factory": factory,
                        "trace_id": trace_id, "findings": unwaived}
        else:
            # The gate-off path still lands an admission event so the
            # causal chain submit -> queue -> admission -> … is
            # unbroken in every configuration.
            self.queue.log_event("admission", tenant=tenant,
                                 factory=factory, trace_id=trace_id,
                                 secs=0.0, skipped=True, findings=0)
        job = Job(job_id=self.queue.next_id(tenant), tenant=tenant,
                  factory=factory, factory_kwargs=factory_kwargs,
                  transform=transform, strict=strict,
                  max_depth=max_depth, max_secs=max_secs,
                  budget_units=budget_units, chunk=chunk,
                  frontier_cap=frontier_cap, visited_cap=visited_cap,
                  ladder=tuple(ladder), fault=fault,
                  trace_id=trace_id)
        # Exact-key verdict cache (ISSUE 16 leg a): a structural +
        # budget + knob match returns the cached verdict with ZERO
        # device dispatches — journaled memo_hit, cached=true verdict,
        # near-zero COSTS charge (no flight log to bill).
        if (self.memo is not None and fault is None and spec_fp
                and intro.get("ok")):
            plan = self.memo.plan(
                intro, strict, chunk, frontier_cap, visited_cap,
                tuple(ladder), max_depth, max_secs,
                env=self._memo_env())
            if plan.mode == "hit":
                return self._complete_memo_hit(job, st, plan)
        res = self.queue.submit(job)
        if res.get("accepted"):
            res["trace_id"] = trace_id
            with self._lock:
                self.sched.push(job)
                st["submitted"] += 1
        else:
            self.queue.mark_rejected(tenant, "queue_full",
                                     {"trace_id": trace_id})
            with self._lock:
                st["rejected"] += 1
        self._write_status()
        return res

    def _admit(self, factory, factory_kwargs, transform,
               fp: Optional[str] = None) -> Tuple[List[dict], bool]:
        """The cached admission check; returns ``(findings, cached)``
        so the journal's admission event can tell a paid subprocess
        check from a cache hit (their latencies differ by ~1000x and
        the trace timeline should say which one a tenant waited on).

        With memoization on, the cache keys on the STRUCTURAL spec
        fingerprint (ISSUE 16 satellite) — the same identity the memo
        store uses, so a rename-only resubmit hits both caches and
        admission can never disagree with memoization about what a
        spec IS.  Without a fingerprint (memo off, introspection
        failed) the legacy source key applies."""
        key = (("fp", fp) if fp else
               (factory,
                json.dumps(factory_kwargs or {}, sort_keys=True),
                transform or ""))
        with self._lock:
            cached = self._admission_cache.get(key)
        if cached is not None:
            return cached, True
        findings = admission_check(factory, factory_kwargs, transform,
                                   extra_sys_path=self.extra_sys_path,
                                   env=self.env)
        with self._lock:
            self._admission_cache[key] = findings
        return findings, False

    # ---------------------------------------------------------- memo

    def _memo_env(self) -> dict:
        """The env the warden CHILD will actually see (os.environ
        overlaid with the server's env) — the memo key's pack/symmetry
        gates must be resolved exactly the way the engine will."""
        return {**os.environ, **self.env}

    def _introspect(self, factory, factory_kwargs,
                    transform) -> Optional[dict]:
        """Cached structural introspection (service/memo.py child).
        The cache key includes the factory MODULE FILE's content hash:
        a tenant editing the module in place gets a fresh child (fresh
        interpreter, no stale ``sys.modules``), so an edited spec can
        never ride a stale fingerprint into the verdict cache."""
        if self.memo is None:
            return None
        src = memo_mod.factory_source_hash(factory, self.extra_sys_path)
        key = (factory,
               json.dumps(factory_kwargs or {}, sort_keys=True,
                          default=repr),
               transform or "", src or "?")
        with self._lock:
            hit = self._intro_cache.get(key)
        if hit is not None:
            return hit
        intro = memo_mod.introspect_child(
            factory, factory_kwargs, transform,
            extra_sys_path=self.extra_sys_path, env=self.env)
        if not intro.get("ok"):
            self.queue.log_event(
                "memo", mode="introspect_failed", factory=factory,
                error=str(intro.get("error"))[:200])
        with self._lock:
            self._intro_cache[key] = intro
        return intro

    def _cached_verdict(self, job: Job, plan) -> dict:
        cached = plan.verdict or {}
        return {
            "job_id": job.job_id, "tenant": job.tenant,
            "trace_id": job.trace_id,
            "budget_units": job.budget_units,
            "status": "done",
            "end": cached.get("end"),
            "unique": cached.get("unique"),
            "explored": cached.get("explored"),
            "depth": cached.get("depth"),
            "engine": cached.get("engine"),
            "predicate": cached.get("predicate"),
            "witness": cached.get("witness"),
            "attempts": 0, "failovers": 0, "child_restarts": 0,
            "knob_shrinks": 0, "rung_steps": 0,
            "resumed_from_depth": 0, "degraded": False, "deaths": [],
            "cached": True, "run_dir": self.job_dir(job.job_id),
            "elapsed_secs": 0.0,
        }

    def _complete_memo_hit(self, job: Job, st: dict, plan) -> dict:
        """Land a verdict-cache hit: the job enters and leaves the
        journal in one motion (submit -> memo_hit -> done), the COSTS
        charge bills its exact counters against NO flight log (device
        seconds ~ 0), and no scheduler/warden work happens at all."""
        res = self.queue.submit(job)
        if not res.get("accepted"):
            self.queue.mark_rejected(job.tenant, "queue_full",
                                     {"trace_id": job.trace_id})
            with self._lock:
                st["rejected"] += 1
            self._write_status()
            return res
        res["trace_id"] = job.trace_id
        verdict = self._cached_verdict(job, plan)
        self.queue.log_event(
            "memo_hit", job_id=job.job_id, tenant=job.tenant,
            trace_id=job.trace_id, sig=plan.sig,
            device_secs_saved=round(plan.base_device_secs, 4))
        self.queue.mark_done(job.job_id, {
            "end": verdict["end"], "unique": verdict["unique"],
            "explored": verdict["explored"], "depth": verdict["depth"],
            "attempts": 0, "degraded": False, "cached": True})
        self._charge(verdict, self.job_dir(job.job_id))
        self.memo.bump("hits")
        self.memo.bump("device_secs_saved", plan.base_device_secs)
        with self._lock:
            st["submitted"] += 1
            st["completed"] += 1
            st["verdicts"] += 1
            self.results.append(verdict)
        self._write_status()
        res["verdict"] = verdict
        res["memo"] = "hit"
        return res

    def _memo_plan(self, job: Job):
        """(intro, plan) for one job at RUN time (restart replay safe:
        recomputes from the intro cache or a fresh child)."""
        if self.memo is None:
            return None, None
        intro = self._introspect(job.factory, job.factory_kwargs,
                                 job.transform)
        if not intro or not intro.get("ok"):
            return intro, None
        plan = self.memo.plan(
            intro, job.strict, job.chunk, job.frontier_cap,
            job.visited_cap, tuple(job.ladder), job.max_depth,
            job.max_secs, env=self._memo_env())
        if job.fault is not None and plan.mode == "hit":
            # Fault experiments always RUN (the injected condition is
            # the point); warm/incremental seeding still applies — the
            # seeded job survives its SIGKILL via the normal resume.
            return intro, None
        return intro, plan

    # ------------------------------------------------------------ run job

    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.root, "jobs", job_id)

    def run_job(self, job: Job) -> dict:
        """Run ONE job to a verdict or a structured failure, applying
        the bounded degrade-and-retry policy (scheduler.degrade) across
        warden launches.  Every attempt resumes the job's own durable
        checkpoint; the fault domain is the warden child tree — nothing
        here can take the server down."""
        from dslabs_tpu.tpu.supervisor import SupervisorExhausted
        from dslabs_tpu.tpu.warden import Warden

        rd = self.job_dir(job.job_id)
        os.makedirs(rd, exist_ok=True)
        ckpt = os.path.join(rd, "ckpt.npz")
        intro, mplan = self._memo_plan(job)
        if mplan is not None and mplan.mode == "hit":
            # A sibling job archived this exact signature between
            # submit and run (drain ordering) — land it as a hit.
            verdict = self._cached_verdict(job, mplan)
            self.queue.log_event(
                "memo_hit", job_id=job.job_id, tenant=job.tenant,
                trace_id=job.trace_id, sig=mplan.sig,
                device_secs_saved=round(mplan.base_device_secs, 4))
            self.queue.mark_done(job.job_id, {
                "end": verdict["end"], "unique": verdict["unique"],
                "explored": verdict["explored"],
                "depth": verdict["depth"], "attempts": 0,
                "degraded": False, "cached": True})
            self._charge(verdict, rd)
            self.memo.bump("hits")
            self.memo.bump("device_secs_saved", mplan.base_device_secs)
            return verdict
        seeded = False
        if (mplan is not None and mplan.mode in ("warm", "incremental")
                and mplan.seed_ckpt and not os.path.exists(ckpt)):
            # Pre-seed the job's own durable checkpoint from the
            # archived signature state; the warden child resumes it
            # via the EXISTING checkpoint path — no new plumbing in
            # the engine, and a crash mid-run keeps the job's own
            # (deeper) checkpoint on later attempts.
            tmp = ckpt + ".seed"
            shutil.copyfile(mplan.seed_ckpt, tmp)
            os.replace(tmp, ckpt)
            seeded = True
            self.memo.bump("warm_starts" if mplan.mode == "warm"
                           else "incremental")
            if mplan.mode == "incremental":
                self.memo.bump("levels_skipped", mplan.levels_skipped)
            self.queue.log_event(
                "memo", mode=mplan.mode, job_id=job.job_id,
                tenant=job.tenant, trace_id=job.trace_id,
                sig=mplan.sig, seed_depth=mplan.seed_depth,
                levels_skipped=mplan.levels_skipped,
                reason=mplan.reason)
        elif self.memo is not None:
            self.memo.bump("misses")
        wenv = dict(self.env)
        if self.memo is not None:
            # Per-level archives for future incremental re-checks.
            wenv["DSLABS_MEMO_LEVELS"] = os.path.join(rd, "levels")
        plan = AttemptPlan(attempt=1, chunk=job.chunk,
                           ladder=tuple(job.ladder))
        deaths: List[dict] = []
        t0 = time.time()
        while True:
            self.queue.mark_started(job.job_id, plan.attempt)
            w = Warden(
                factory=job.factory,
                factory_kwargs=job.factory_kwargs,
                transform=job.transform,
                ladder=plan.ladder,
                checkpoint_path=ckpt, checkpoint_every=1,
                strict=job.strict, max_depth=job.max_depth,
                max_secs=job.max_secs, chunk=plan.chunk,
                frontier_cap=job.frontier_cap,
                visited_cap=job.visited_cap,
                # Injected faults model an environment condition of the
                # FIRST attempt; a scheduler-level retry runs clean.
                fault=(job.fault if plan.attempt == 1 else None),
                env=wenv,
                extra_sys_path=self.extra_sys_path,
                elastic=self.elastic,
                # Trace propagation (ISSUE 13): the warden forwards
                # both via DSLABS_TRACE_ID/DSLABS_PARENT_SPAN, and the
                # attempt span id is DERIVED from the journal's start
                # record, so the child's flight-log meta links back to
                # this exact attempt with no extra journal field.
                trace_id=job.trace_id,
                parent_span=plan.span_id(job.job_id),
                **self.warden_kwargs)
            try:
                out = w.run(resume=plan.attempt > 1 or seeded)
            except SupervisorExhausted:
                deaths += [{"rung": d.rung, "kind": d.kind,
                            "detail": d.detail[:200]} for d in w.deaths]
                kind = w.deaths[-1].kind if w.deaths else "failed"
                if seeded and any("Checkpoint" in d.get("detail", "")
                                  for d in deaths):
                    # A refused/torn memo seed must never fail the
                    # job: abandon the seed loudly and run cold.
                    for p in (ckpt, ckpt + ".prev"):
                        try:
                            os.remove(p)
                        except OSError:
                            pass
                    seeded = False
                    deaths = []
                    self.queue.log_event(
                        "memo", mode="seed_abandoned",
                        job_id=job.job_id, trace_id=job.trace_id,
                        detail=(w.deaths[-1].detail[:200]
                                if w.deaths else ""))
                    continue
                nxt = degrade(plan, kind, self.retry)
                if nxt is None:
                    failure = {
                        "job_id": job.job_id, "tenant": job.tenant,
                        "trace_id": job.trace_id,
                        "status": "failed", "kind": kind,
                        "attempts": plan.attempt,
                        "knob_shrinks": plan.knob_shrinks,
                        "rung_steps": plan.rung_steps,
                        "deaths": deaths,
                        "budget_units": job.budget_units,
                        "run_dir": rd,
                        "elapsed_secs": round(time.time() - t0, 2),
                    }
                    self.queue.mark_failed(job.job_id, {
                        "kind": kind, "attempts": plan.attempt,
                        "deaths": len(deaths)})
                    self._charge(failure, rd)
                    return failure
                time.sleep(self.retry.backoff(plan.attempt - 1))
                plan = nxt
                continue
            except BaseException as e:  # noqa: BLE001 — structured, never silent
                failure = {
                    "job_id": job.job_id, "tenant": job.tenant,
                    "trace_id": job.trace_id,
                    "status": "failed", "kind": "error",
                    "error": f"{type(e).__name__}: {e}"[:300],
                    "attempts": plan.attempt, "deaths": deaths,
                    "budget_units": job.budget_units,
                    "run_dir": rd,
                    "elapsed_secs": round(time.time() - t0, 2),
                }
                self.queue.mark_failed(job.job_id, {
                    "kind": "error",
                    "error": failure["error"][:200]})
                self._charge(failure, rd)
                return failure
            deaths += [{"rung": d.rung, "kind": d.kind,
                        "detail": d.detail[:200]} for d in w.deaths]
            verdict = {
                "job_id": job.job_id, "tenant": job.tenant,
                "trace_id": job.trace_id,
                "budget_units": job.budget_units,
                "status": "done",
                "end": out.end_condition,
                "unique": out.unique_states,
                "explored": out.states_explored,
                "depth": out.depth,
                "engine": out.engine,
                "predicate": out.predicate_name,
                "witness": memo_mod.witness_digest(
                    out.predicate_name, out.violating_state,
                    out.goal_state, out.trace),
                "attempts": plan.attempt,
                "failovers": out.failovers,
                "child_restarts": out.child_restarts,
                "knob_shrinks": plan.knob_shrinks,
                "rung_steps": plan.rung_steps,
                "resumed_from_depth": out.resumed_from_depth,
                "degraded": bool(deaths or plan.knob_shrinks
                                 or plan.rung_steps),
                "deaths": deaths,
                "run_dir": rd,
                "elapsed_secs": round(time.time() - t0, 2),
            }
            self.queue.mark_done(job.job_id, {
                "end": out.end_condition, "unique": out.unique_states,
                "explored": out.states_explored, "depth": out.depth,
                "attempts": plan.attempt,
                "degraded": verdict["degraded"]})
            self._charge(verdict, rd)
            if self.memo is not None and intro and intro.get("ok"):
                try:
                    dsecs = tracing.CostMeter.flight_costs(
                        os.path.join(rd, "flight.jsonl"))["device_secs"]
                except Exception:  # noqa: BLE001
                    dsecs = 0.0
                try:
                    fields = memo_mod.key_fields(
                        intro, job.strict, job.chunk, job.frontier_cap,
                        job.visited_cap, tuple(job.ladder),
                        env=self._memo_env())
                    self.memo.archive(intro, fields, verdict, rd, dsecs)
                    self.memo.record_verdict(fields, job.max_depth,
                                             job.max_secs, verdict,
                                             dsecs)
                except Exception as e:  # noqa: BLE001 — reuse is best-effort
                    self.queue.log_event(
                        "memo", mode="archive_failed",
                        job_id=job.job_id,
                        error=f"{type(e).__name__}: {e}"[:200])
                if seeded and mplan is not None:
                    self.memo.bump(
                        "device_secs_saved",
                        max(0.0, mplan.base_device_secs - dsecs))
            return verdict

    def run_job_batch(self, jobs: List["Job"]) -> List[dict]:
        """Run a lane-compatible job group as ONE lane-batch warden
        child (ISSUE 14, tpu/lanes.py): every job keeps its own run
        dir + checkpoint (SIGKILL mid-batch resumes each lane from its
        own dump), continuous batching refills drained lanes from the
        group, and a poisoned lane is EVICTED to a solo retry
        (re-queued with ``solo=True``) — it never burns a lane-mate's
        verdict.  Returns the verdicts/failures that LANDED; evicted
        jobs return to the scheduler instead."""
        from dslabs_tpu.tpu.lanes import LaneBatchWarden, job_signature

        with self._lock:
            self._lane_seq += 1
            batch_id = f"batch-{self._lane_seq:05d}"
        bdir = os.path.join(self.root, "lanes", batch_id)
        os.makedirs(bdir, exist_ok=True)
        first = jobs[0]
        lane_jobs = []
        for job in jobs:
            rd = self.job_dir(job.job_id)
            os.makedirs(rd, exist_ok=True)
            lane_jobs.append({
                "job_id": job.job_id,
                "max_depth": job.max_depth,
                "max_secs": job.max_secs,
                "checkpoint_path": os.path.join(rd, "ckpt.npz"),
                "checkpoint_every": 1,
                "trace_id": job.trace_id})
            self.queue.mark_started(job.job_id, 1)
        n_lanes = min(self.lanes, len(jobs))
        # The journal join the trace assembler + packing stats read:
        # which jobs shared which batch, and where its flight log is.
        self.queue.log_event(
            "lane_batch", batch=batch_id,
            jobs=[j.job_id for j in jobs], lanes=n_lanes,
            run_dir=bdir)
        t0 = time.time()
        w = LaneBatchWarden(
            factory=first.factory,
            factory_kwargs=first.factory_kwargs,
            transform=first.transform,
            jobs=lane_jobs, n_lanes=n_lanes,
            strict=first.strict, chunk=first.chunk,
            frontier_cap=first.frontier_cap,
            visited_cap=first.visited_cap,
            run_dir=bdir, swap=self.lane_swap,
            env=dict(self.env),
            extra_sys_path=self.extra_sys_path,
            telemetry=self.telemetry)
        try:
            res = w.run()
        except BaseException as e:  # noqa: BLE001 — structured, never silent
            from dslabs_tpu.tpu.lanes import LaneBatchResult

            res = LaneBatchResult(
                {}, {j.job_id: f"batch:error: {type(e).__name__}: "
                     f"{e}"[:300] for j in jobs})
        by_id = {j.job_id: j for j in jobs}
        elapsed = round(time.time() - t0, 2)
        bflight = os.path.join(bdir, "flight.jsonl")
        results: List[dict] = []
        for jid, out in res.outcomes.items():
            job = by_id[jid]
            verdict = {
                "job_id": jid, "tenant": job.tenant,
                "trace_id": job.trace_id,
                "budget_units": job.budget_units,
                "status": "done",
                "end": out.end_condition,
                "unique": out.unique_states,
                "explored": out.states_explored,
                "depth": out.depth,
                "engine": "lanes",
                "attempts": 1,
                "failovers": 0,
                "child_restarts": out.child_restarts,
                "knob_shrinks": 0, "rung_steps": 0,
                "resumed_from_depth": out.resumed_from_depth,
                "degraded": out.child_restarts > 0,
                "deaths": [{"rung": "lanes", "kind": d["kind"],
                            "detail": d["detail"][:200]}
                           for d in w.deaths],
                "run_dir": self.job_dir(jid),
                "lane_batch": batch_id,
                "lane": out.lane,
                "lanes": out.lane_width,
                "lane_share": out.lane_share,
                "elapsed_secs": elapsed,
            }
            self.queue.mark_done(jid, {
                "end": out.end_condition, "unique": out.unique_states,
                "explored": out.states_explored, "depth": out.depth,
                "attempts": 1, "degraded": verdict["degraded"],
                "lane_batch": batch_id})
            # The COSTS charge reads the BATCH flight log scaled by
            # the lane's share — shares sum to 1.0, so the shared
            # dispatch stream is billed exactly once.
            try:
                self.costs.charge(verdict, bflight)
            except Exception:  # noqa: BLE001 — accounting is best-effort
                pass
            results.append(verdict)
        requeued = []
        for jid, err in res.errors.items():
            job = by_id[jid]
            self.queue.log_event("lane_evicted", job_id=jid,
                                 batch=batch_id, error=err[:200])
            requeued.append(dataclasses.replace(job, solo=True))
        with self._lock:
            for j in requeued:
                self.sched.push(j)
            ls = self.lane_stats
            ls["batches"] += 1
            ls["jobs"] += len(jobs)
            ls["swaps"] += res.swaps
            ls["evicted"] += len(res.errors)
            ls["occupancy_sum"] += res.occupancy
            sig = job_signature(first) or "?"
            per = ls["by_signature"].setdefault(
                sig, {"batches": 0, "jobs": 0})
            per["batches"] += 1
            per["jobs"] += len(jobs)
        return results

    def _charge(self, verdict: dict, run_dir: str) -> None:
        """Feed the cost meter (never fatal — accounting must not take
        a verdict down): the verdict's exact counters + the run dir's
        flight log become one COSTS.jsonl record."""
        try:
            self.costs.charge(
                verdict, os.path.join(run_dir, "flight.jsonl"))
        except Exception:  # noqa: BLE001 — accounting is best-effort
            pass

    # ---------------------------------------------------------- retention

    def retention_sweep(self) -> List[str]:
        """Prune the oldest FINISHED jobs' run dirs down to
        ``self.keep`` (DSLABS_SERVICE_KEEP).  Called at scheduler idle
        (drain start/end) — never while that job could still run:
        running and queued jobs are excluded by construction, and a
        pruned job keeps its journal/ledger records (only the run dir
        — checkpoint, flight log, compile cache — goes).  Each prune
        is journaled and, when a recorder is attached, a telemetry
        event."""
        import shutil

        with self._lock:
            busy = {j.job_id
                    for q in self.sched._queues.values() for j in q}
            running = {t for t, n in self._running.items() if n > 0}
        def _seq(jid: str) -> int:
            try:
                return int(jid.rsplit("-", 1)[-1])
            except ValueError:
                return 0

        finished = []
        for jid, rec in sorted(self.queue.records.items(),
                               key=lambda kv: _seq(kv[0])):
            if rec.get("status") not in ("done", "failed"):
                continue
            if jid in busy or rec.get("tenant") in running:
                continue
            d = self.job_dir(jid)
            if os.path.isdir(d):
                finished.append(jid)
        pruned: List[str] = []
        if self.keep >= 0 and len(finished) > self.keep:
            for jid in finished[:len(finished) - self.keep]:
                try:
                    shutil.rmtree(self.job_dir(jid))
                except OSError:
                    continue
                pruned.append(jid)
                self.queue.log_event("prune", job_id=jid,
                                     keep=self.keep)
                if self.telemetry is not None:
                    self.telemetry.event("prune", job_id=jid,
                                         keep=self.keep)
        # Lane-batch run dirs (ISSUE 14) age out under the same knob:
        # the sweep runs at scheduler idle, so no batch child is live;
        # the journal's lane_batch events (the trace join) survive.
        lanes_root = os.path.join(self.root, "lanes")
        if self.keep >= 0 and os.path.isdir(lanes_root):
            try:
                batches = sorted(os.listdir(lanes_root))
            except OSError:
                batches = []
            for b in batches[:max(0, len(batches) - self.keep)]:
                try:
                    shutil.rmtree(os.path.join(lanes_root, b))
                except OSError:
                    continue
                pruned.append(b)
                self.queue.log_event("prune", batch=b, keep=self.keep)
        return pruned

    # -------------------------------------------------------------- drain

    def drain(self, max_secs: Optional[float] = None,
              workers: Optional[int] = None) -> dict:
        """Run the backlog to completion (or the deadline) and return
        the aggregate summary — per-tenant throughput, fairness index,
        queue state.  Each worker thread coordinates; the actual
        search work lives in per-job warden child processes."""
        n_workers = max(1, workers if workers is not None
                        else self.workers)
        deadline = (time.time() + max_secs) if max_secs else None
        t0 = time.time()

        from dslabs_tpu.tpu.lanes import job_signature

        def worker():
            while True:
                if deadline is not None and time.time() > deadline:
                    return
                picked: List = []
                with self._lock:
                    if self.lanes > 1:
                        # Lane packer (ISSUE 14): group lane-compatible
                        # queued jobs under the same DRR quota/deficit
                        # semantics; over-picking up to 2L feeds
                        # continuous batching's swap-ins.
                        picked = self.sched.pick_batch(
                            self._running, job_signature,
                            self.lanes * (2 if self.lane_swap else 1))
                    else:
                        job = self.sched.pick(self._running)
                        picked = [job] if job is not None else []
                    if not picked:
                        if self.sched.pending() == 0 and self._active == 0:
                            return
                    else:
                        for job in picked:
                            self.queue.pop(job.job_id)
                            self._running[job.tenant] = \
                                self._running.get(job.tenant, 0) + 1
                            self._active += 1
                            st = self.stats.setdefault(job.tenant,
                                                       _zero_stats())
                            st["budget_spent"] += job.budget_units
                if not picked:
                    time.sleep(0.05)
                    continue
                try:
                    if len(picked) == 1:
                        res_list = [self.run_job(picked[0])]
                    else:
                        res_list = self.run_job_batch(picked)
                finally:
                    with self._lock:
                        for job in picked:
                            self._running[job.tenant] -= 1
                            self._active -= 1
                with self._lock:
                    for res in res_list:
                        st = self.stats.setdefault(res["tenant"],
                                                   _zero_stats())
                        if res.get("status") == "done":
                            st["completed"] += 1
                            st["verdicts"] += 1
                        else:
                            st["failed"] += 1
                        self.results.append(res)
                self._write_status()

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"dslabs-service-worker-{i}")
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # The scheduler is idle here (workers drained or deadline hit):
        # the retention sweep runs now, never beside live jobs.
        self.retention_sweep()
        self._write_status(force=True)
        with self._lock:
            results = list(self.results)
            per_tenant = {t: dict(s) for t, s in self.stats.items()}
        done = [r for r in results if r.get("status") == "done"]
        failed = [r for r in results if r.get("status") != "done"]
        wall = max(time.time() - t0, 1e-9)
        for stats in per_tenant.values():
            stats["verdicts_per_min"] = round(
                stats["verdicts"] / wall * 60.0, 2)
        totals = self.costs.totals()
        return {
            "jobs": len(results),
            "completed": len(done),
            "failed": len(failed),
            "verdicts_per_min": round(len(done) / wall * 60.0, 2),
            "fairness_index": fairness_index(per_tenant),
            # Lane amortisation (ISSUE 14): packing decisions + the
            # mean dispatches billed per job (share-scaled across lane
            # batches), the number the ledger compare guards.
            "lanes": self._lane_block(),
            # Cross-job reuse (ISSUE 16): hits / warm starts /
            # incremental re-checks and the device-seconds they saved
            # — the multiplier the ledger compare guards.
            "memo": (self.memo.stats_block() if self.memo is not None
                     else {"enabled": False}),
            "dispatches_per_job": totals.get("dispatches_per_job"),
            "per_tenant": per_tenant,
            # The cost ledger's view (tpu/tracing.py CostMeter):
            # per-tenant device-seconds / dispatches / compile split /
            # cost-per-unique-state, and the aggregate headline the
            # ledger compare tracks.
            "costs": self.costs.tenant_summary(),
            "cost_per_unique": totals.get("cost_per_unique"),
            "device_secs": totals.get("device_secs"),
            "queue": self.queue.summary(),
            "wall_secs": round(wall, 2),
            "results": results,
        }

    # ------------------------------------------------------------- status

    def _lane_block(self) -> dict:
        """The ``lanes`` observability block (SERVER_STATUS.json +
        drain summary + ``service status``): batch width/swap config,
        packing decisions, occupancy, evictions, per-signature batch
        sizes."""
        with self._lock:
            ls = self.lane_stats
            return {
                "width": self.lanes,
                "swap": self.lane_swap,
                "batches": ls["batches"],
                "jobs_in_lanes": ls["jobs"],
                "swaps": ls["swaps"],
                "evicted": ls["evicted"],
                "mean_occupancy": (
                    round(ls["occupancy_sum"] / ls["batches"], 3)
                    if ls["batches"] else None),
                "by_signature": {s: dict(v) for s, v
                                 in ls["by_signature"].items()},
            }

    def server_status(self) -> dict:
        qs = self.queue.summary()
        cost_ledger = self.costs.tenant_summary()
        lane_block = self._lane_block()
        with self._lock:
            pending = self.sched.pending_by_tenant()
            tenants = {}
            for t in set(list(self.stats) + list(pending)
                         + list(self._running)):
                s = self.stats.get(t, _zero_stats())
                tenants[t] = {
                    "pending": pending.get(t, 0),
                    "running": self._running.get(t, 0),
                    "completed": s["completed"],
                    "failed": s["failed"],
                    "rejected": s["rejected"],
                    "budget_spent": round(s["budget_spent"], 3),
                    # The auditable per-tenant cost ledger (ISSUE 13):
                    # what the tenant's budget actually bought, from
                    # COSTS.jsonl — device seconds, dispatches, the
                    # compile-vs-search split, cost per unique state.
                    "costs": cost_ledger.get(t),
                }
            return {
                "t": "server_status",
                "updated": round(time.time(), 3),
                "pid": os.getpid(),
                "workers": self.workers,
                "queue_depth": qs["queue_depth"],
                "queue_cap": qs["queue_cap"],
                "backpressure": qs["backpressure"],
                "journal_error": qs["journal_error"],
                "tenants": tenants,
                "fairness_index": fairness_index(self.stats),
                # Batched-lane observability (ISSUE 14): occupancy,
                # packing decisions, per-signature batch sizes.
                "lanes": lane_block,
                # Cross-job memoization counters (ISSUE 16).
                "memo": (self.memo.stats_block()
                         if self.memo is not None
                         else {"enabled": False}),
            }

    def _write_status(self, force: bool = False) -> None:
        """Atomic SERVER_STATUS.json rewrite (tmp + ``os.replace``) —
        a reader or a SIGKILL never sees a torn file; an unwritable
        root disables the monitor, never the service."""
        st = self.server_status()
        tmp = self.status_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps(st))
            os.replace(tmp, self.status_path)
        except OSError:
            self.status_path = None

    def close(self) -> None:
        self.queue.close()
        self.costs.close()


# ------------------------------------------------------- admission child

def _resolve(ref: str):
    import importlib

    mod, _, name = ref.partition(":")
    obj = importlib.import_module(mod)
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _admission_main() -> int:
    """The CPU-pinned admission child: read one factory spec from
    stdin, lint its module with the conformance linter, build the spec
    object (NEVER a twin/engine — no search is constructed here), run
    the live C4 introspection when it is a ProtocolSpec, and print the
    waiver-applied findings as one JSON line.  Any escape is the
    parent's "child died" rejection — a hostile spec cannot get past
    the gate by crashing it."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — jax may be absent for pure lint
        pass
    spec = json.load(sys.stdin)
    factory = spec["factory"]
    mod_name = factory.partition(":")[0]

    from dslabs_tpu.analysis import conformance
    from dslabs_tpu.analysis.core import (Finding, apply_waivers,
                                          load_waivers, repo_root)

    findings: List[Finding] = []

    def _gate(message: str, code: str = "C4", line: int = 0) -> None:
        findings.append(Finding(
            code=code, leg="conformance", path=factory,
            obj="<admission>", line=line, message=message))

    mod = None
    try:
        import importlib

        mod = importlib.import_module(mod_name)
    except BaseException as e:  # noqa: BLE001 — import errors are findings
        _gate(f"factory import failed: {type(e).__name__}: {e}")
    if mod is not None and getattr(mod, "__file__", None):
        try:
            with open(mod.__file__) as f:
                src = f.read()
            rel = os.path.relpath(mod.__file__, repo_root())
            if rel.startswith(".."):
                rel = mod.__file__
            findings += conformance.lint_source(src, rel)
        except OSError as e:
            _gate(f"factory module unreadable: {e}")
        from dslabs_tpu.tpu.compiler import ProtocolSpec, SpecError

        try:
            proto = _resolve(factory)(**(spec.get("factory_kwargs")
                                         or {}))
            if spec.get("transform"):
                proto = _resolve(spec["transform"])(proto)
            if isinstance(proto, ProtocolSpec):
                findings += conformance.check_spec(
                    proto, origin=rel if mod else factory)
        except SpecError as e:
            _gate(str(e), code=e.code, line=e.line or 0)
        except BaseException as e:  # noqa: BLE001 — a raising factory is unsound
            _gate(f"factory raised {type(e).__name__}: {e}")
    try:
        apply_waivers(findings, load_waivers())
    except ValueError as e:
        _gate(f"waiver file malformed: {e}")
    sys.stdout.write(json.dumps(
        {"findings": [f.as_dict() for f in findings]}) + "\n")
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(_admission_main())
