"""Bounded persistent job queue for the checking service (ISSUE 11).

The service's front door: jobs are (protocol factory spec, budget,
tenant id) records in a **JSONL journal** beside the service run dir —
the same crash-safety discipline as the rest of the repo's durable
artifacts:

* **Appends are line-buffered** (one ``write`` per record, like the
  telemetry flight recorder), so a SIGKILL mid-append leaves at most
  ONE torn tail line;
* **Replay tolerates the torn tail** exactly the way the
  flight-recorder reader does (``telemetry.read_flight``): the final
  unparsable line is the expected crash shape, a torn line anywhere
  else is corruption and raises;
* **Compaction is tmp + ``os.replace``** (the checkpoint-style atomic
  rewrite, tpu/checkpoint.py): a kill mid-compact leaves the previous
  complete journal.

Backpressure is **structured, never exceptional**: ``submit`` on a full
queue returns ``{"accepted": False, "rejected": True,
"retry_after_secs": …, "queue_depth": …}`` — it never raises and never
blocks (the caller is a tenant-facing front end; an exception or a
stall there IS the outage).  Replay re-queues jobs that were marked
``start``\\ ed but never finished, so a crashed server resumes its
backlog — each such job also resumes its own run-dir checkpoint, the
per-job fault domain the server builds (service/server.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = ["Job", "ServiceQueue", "JOURNAL_NAME", "replay_journal"]

JOURNAL_NAME = "journal.jsonl"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclasses.dataclass
class Job:
    """One tenant submission: everything a warden child needs to
    rebuild and run the search, plus the scheduler's accounting.  The
    protocol crosses the process boundary as a ``"module:callable"``
    factory spec (tpu/warden.py) — live objects never enter the
    journal."""

    job_id: str
    tenant: str
    factory: str
    factory_kwargs: Optional[dict] = None
    transform: Optional[str] = None
    strict: bool = True
    max_depth: Optional[int] = None
    max_secs: Optional[float] = None
    # DRR cost / billing unit: the fairness ledger charges this many
    # quanta when the job is picked (scheduler.py).
    budget_units: float = 1.0
    chunk: int = 1 << 10
    frontier_cap: int = 1 << 14
    visited_cap: int = 1 << 20
    ladder: Tuple[str, ...] = ("device", "host")
    # Deterministic warden-side fault injection (tests/chaos only) —
    # applied on the FIRST scheduler attempt, so a retry models the
    # environment condition clearing.
    fault: Optional[dict] = None
    # Batched-lane opt-out (ISSUE 14, tpu/lanes.py): set when a
    # poisoned lane evicts the job to a solo retry — the lane packer
    # (lanes.job_signature) reads it as "never batch this again".
    solo: bool = False
    submitted_at: float = 0.0
    # Causal-trace identity (ISSUE 13, tpu/tracing.py): minted at
    # submit, persisted by the journal, stamped on every journal event
    # and warden child env — the one key the trace assembler joins the
    # journal, SERVER_STATUS, and the per-job flight logs on.
    trace_id: Optional[str] = None

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ladder"] = list(self.ladder)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Job":
        kw = {f.name: d[f.name] for f in dataclasses.fields(cls)
              if f.name in d}
        kw["ladder"] = tuple(kw.get("ladder") or ("device", "host"))
        return cls(**kw)


def replay_journal(path: str) -> Tuple[List[Job], Dict[str, dict], int]:
    """Rebuild queue state from the journal, tolerating one torn tail
    line (the flight-recorder contract — telemetry.read_flight does
    the parsing).  Returns ``(pending_jobs, records, max_seq)``:
    jobs submitted but never finished (``start``\\ ed-but-unfinished
    ones re-queue — the crash-recovery path), the per-job record map,
    and the highest job sequence number seen (so new ids never
    collide)."""
    from dslabs_tpu.tpu.telemetry import read_flight

    records: Dict[str, dict] = {}
    max_seq = 0
    if not os.path.exists(path):
        return [], {}, 0
    for rec in read_flight(path):
        t = rec.get("t")
        jid = rec.get("job_id")
        if t == "submit" and isinstance(rec.get("job"), dict):
            job = rec["job"]
            jid = job.get("job_id")
            records[jid] = {"job": job, "status": "pending",
                            "tenant": job.get("tenant")}
            try:
                max_seq = max(max_seq, int(jid.rsplit("-", 1)[-1]))
            except (ValueError, AttributeError):
                pass
        elif jid in records:
            if t == "start":
                records[jid]["status"] = "running"
                records[jid]["attempt"] = rec.get("attempt")
            elif t == "done":
                records[jid]["status"] = "done"
                records[jid]["verdict"] = rec.get("verdict")
            elif t == "failed":
                records[jid]["status"] = "failed"
                records[jid]["failure"] = rec.get("failure")
    pending = [Job.from_dict(r["job"]) for r in records.values()
               if r["status"] in ("pending", "running")]
    for r in records.values():
        if r["status"] == "running":       # crash-interrupted: re-queue
            r["status"] = "pending"
    pending.sort(key=lambda j: (j.submitted_at, j.job_id))
    return pending, records, max_seq


class ServiceQueue:
    """The bounded persistent queue.  All mutation goes through the
    journal first (write-ahead), then memory; every public method is
    thread-safe and non-blocking."""

    def __init__(self, root: str, cap: Optional[int] = None,
                 retry_after_base: Optional[float] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.cap = cap if cap is not None else _env_int(
            "DSLABS_SERVICE_QUEUE_CAP", 64)
        # Backpressure hint scale: retry_after_secs grows linearly with
        # the depth of the queue the rejected tenant is waiting behind.
        self.retry_after_base = (retry_after_base
                                 if retry_after_base is not None
                                 else _env_float(
                                     "DSLABS_SERVICE_RETRY_AFTER", 2.0))
        self.journal_path = os.path.join(self.root, JOURNAL_NAME)
        self._lock = threading.Lock()
        pending, self.records, self._seq = replay_journal(
            self.journal_path)
        self.pending: "deque[Job]" = deque(pending)
        self.journal_error: Optional[str] = None
        self._fh = None
        self._open_journal()

    # ------------------------------------------------------------ journal

    def _open_journal(self) -> None:
        try:
            self._fh = open(self.journal_path, "a", buffering=1)
        except OSError as e:
            # A read-only root degrades to RAM-only queueing (the
            # telemetry convention): the service keeps serving, the
            # durability loss is attributable on journal_error.
            self.journal_error = f"{type(e).__name__}: {e}"
            self._fh = None

    def _append(self, rec: dict) -> None:
        if self._fh is None:
            return
        # Every journal event is timestamped (ISSUE 13): the trace
        # assembler derives queue-wait / attempt / verdict boundaries
        # from these, so the causal timeline exists on disk alone.
        rec.setdefault("ts", round(time.time(), 3))
        try:
            self._fh.write(json.dumps(rec) + "\n")
        except (OSError, ValueError) as e:
            self.journal_error = f"{type(e).__name__}: {e}"
            self._fh = None

    def log_event(self, kind: str, **fields) -> None:
        """Append one free-form operational event to the journal (the
        admission gate's timing, retention prunes, …) — replay ignores
        unknown kinds, the trace assembler reads them."""
        with self._lock:
            self._append({"t": kind, **fields})

    def compact(self) -> None:
        """Rewrite the journal to the live state only (dropping the
        event history of finished jobs) via tmp + ``os.replace`` — the
        checkpoint-style atomic rewrite; a kill mid-compact leaves the
        previous complete journal."""
        with self._lock:
            lines = []
            for jid in sorted(self.records):
                r = self.records[jid]
                lines.append(json.dumps({"t": "submit", "job": r["job"]}))
                if r["status"] == "done":
                    lines.append(json.dumps(
                        {"t": "done", "job_id": jid,
                         "verdict": r.get("verdict")}))
                elif r["status"] == "failed":
                    lines.append(json.dumps(
                        {"t": "failed", "job_id": jid,
                         "failure": r.get("failure")}))
            tmp = self.journal_path + ".tmp"
            try:
                if self._fh is not None:
                    self._fh.close()
                with open(tmp, "w") as f:
                    f.write("".join(line + "\n" for line in lines))
                os.replace(tmp, self.journal_path)
            except OSError as e:
                self.journal_error = f"{type(e).__name__}: {e}"
            finally:
                self._open_journal()

    # ------------------------------------------------------------- submit

    def depth(self) -> int:
        with self._lock:
            return len(self.pending)

    def next_id(self, tenant: str) -> str:
        with self._lock:
            self._seq += 1
            return f"{tenant}-{self._seq:06d}"

    def submit(self, job: Job) -> dict:
        """Enqueue one job.  On a FULL queue this returns the
        structured retry-after rejection — it never raises and never
        blocks (pinned by tests/test_service.py)."""
        with self._lock:
            depth = len(self.pending)
            if depth >= self.cap:
                return {
                    "accepted": False,
                    "rejected": True,
                    "reason": "queue_full",
                    "retry_after_secs": round(
                        self.retry_after_base * max(1, depth), 1),
                    "queue_depth": depth,
                    "queue_cap": self.cap,
                }
            if not job.submitted_at:
                job.submitted_at = round(time.time(), 3)
            self._append({"t": "submit", "job": job.as_dict()})
            self.records[job.job_id] = {"job": job.as_dict(),
                                        "status": "pending",
                                        "tenant": job.tenant}
            self.pending.append(job)
            return {"accepted": True, "job_id": job.job_id,
                    "queue_depth": len(self.pending)}

    def _drop_pending(self, job_id: str) -> None:
        for j in list(self.pending):
            if j.job_id == job_id:
                self.pending.remove(j)
                break

    def pop(self, job_id: str) -> None:
        """Remove a job from the pending deque (the scheduler owns WHICH
        job runs next; the queue only owns durability)."""
        with self._lock:
            self._drop_pending(job_id)

    # ------------------------------------------------------- state marks
    # Every mark also dequeues (idempotent with pop): a started or
    # finished job is by definition no longer queued, so depth() stays
    # honest for callers that drive the queue without a scheduler.

    def mark_started(self, job_id: str, attempt: int) -> None:
        with self._lock:
            self._drop_pending(job_id)
            self._append({"t": "start", "job_id": job_id,
                          "attempt": attempt})
            if job_id in self.records:
                self.records[job_id]["status"] = "running"
                self.records[job_id]["attempt"] = attempt

    def mark_done(self, job_id: str, verdict: dict) -> None:
        with self._lock:
            self._drop_pending(job_id)
            self._append({"t": "done", "job_id": job_id,
                          "verdict": verdict})
            if job_id in self.records:
                self.records[job_id]["status"] = "done"
                self.records[job_id]["verdict"] = verdict

    def mark_failed(self, job_id: str, failure: dict) -> None:
        with self._lock:
            self._drop_pending(job_id)
            self._append({"t": "failed", "job_id": job_id,
                          "failure": failure})
            if job_id in self.records:
                self.records[job_id]["status"] = "failed"
                self.records[job_id]["failure"] = failure

    def mark_rejected(self, tenant: str, reason: str,
                      detail: Optional[dict] = None) -> None:
        """Admission / backpressure rejections are journal events too —
        SERVER_STATUS.json's per-tenant ``rejected`` counter survives a
        restart."""
        with self._lock:
            self._append({"t": "rejected", "tenant": tenant,
                          "reason": reason, "detail": detail})

    # ------------------------------------------------------------ summary

    def summary(self) -> dict:
        with self._lock:
            by_status: Dict[str, int] = {}
            for r in self.records.values():
                by_status[r["status"]] = by_status.get(r["status"], 0) + 1
            return {
                "queue_depth": len(self.pending),
                "queue_cap": self.cap,
                "backpressure": len(self.pending) >= self.cap,
                "jobs": by_status,
                "journal": self.journal_path,
                "journal_error": self.journal_error,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
