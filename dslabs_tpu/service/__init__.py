"""Checking-as-a-service (ISSUE 11): the resident multi-tenant search
server — bounded persistent queue (service/queue.py), conformance
admission gate + per-job warden fault domains (service/server.py), and
the fairness-preserving DRR scheduler with taxonomy-driven degradation
(service/scheduler.py).  CLI: ``python -m dslabs_tpu.service``.
Field guide: docs/service.md."""

from dslabs_tpu.service.queue import Job, ServiceQueue, replay_journal
from dslabs_tpu.service.scheduler import (AttemptPlan, DeficitRoundRobin,
                                          RetrySpec, degrade,
                                          fairness_index)
from dslabs_tpu.service.server import (CheckServer, SERVER_STATUS_NAME,
                                       admission_check)

__all__ = ["Job", "ServiceQueue", "replay_journal", "AttemptPlan",
           "DeficitRoundRobin", "RetrySpec", "degrade",
           "fairness_index", "CheckServer", "SERVER_STATUS_NAME",
           "admission_check"]
