"""Persistent XLA compile-cache wiring (one knob, one place).

BENCH_r05 blew its 300 s preflight deadline on COMPILE alone: every
sharded program was rebuilt from scratch every run because nothing wired
JAX's persistent compilation cache outside ad-hoc bench code.  This
module is the single seam:

* ``DSLABS_COMPILE_CACHE=<dir>`` points the cache anywhere (a falsy
  value — ``0`` / ``off`` / ``none`` — disables the default entirely);
* with the knob unset, a search that has a ``checkpoint_path``
  configured defaults to a ``compile_cache/`` directory next to the
  dump (:func:`dslabs_tpu.tpu.checkpoint.default_compile_cache_dir`) —
  a resumable job keeps its compiled programs beside its state;
* an already-configured cache dir (conftest.py, bench.py) is never
  clobbered by a default — only the explicit env knob overrides.

Together with the engines' AOT warm-up (``ShardedTensorSearch
.aot_warmup``) the second run of any config pays near-zero compile: the
warm-up's ``.lower().compile()`` hits the on-disk cache instead of XLA.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["setup", "setup_for_checkpoint", "cache_dir"]

_DISABLED = ("0", "off", "none", "false", "no", "")


def cache_dir() -> Optional[str]:
    """The persistent-compile-cache directory currently in effect."""
    import jax

    return jax.config.jax_compilation_cache_dir


def setup(default_dir: Optional[str] = None) -> Optional[str]:
    """Enable JAX's persistent compilation cache.

    Resolution order: ``DSLABS_COMPILE_CACHE`` (explicit dir, or a
    falsy value to disable) > an already-configured cache dir (left
    untouched) > ``default_dir`` > off.  Returns the directory in
    effect (``None`` = no persistent cache).  Idempotent — safe to call
    from every engine constructor."""
    import jax

    env = os.environ.get("DSLABS_COMPILE_CACHE")
    if env is not None:
        if env.strip().lower() in _DISABLED:
            return None
        path = env
    else:
        current = jax.config.jax_compilation_cache_dir
        if current:
            return current
        if not default_dir:
            return None
        path = default_dir
    if jax.config.jax_compilation_cache_dir != path:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # The runtime holds a cache singleton initialised with the dir
        # at FIRST use — without a reset, a dir change after any cached
        # compile is silently ignored.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover — private API drift
            pass
    # Cache even quick compiles: the same program that builds in
    # seconds on CPU costs minutes on the tunnelled TPU runtime, and
    # the cache key is platform-specific anyway.
    if jax.config.jax_persistent_cache_min_compile_time_secs > 0.5:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    return path


def setup_for_checkpoint(checkpoint_path: Optional[str]) -> Optional[str]:
    """:func:`setup` with the documented default — a ``compile_cache/``
    dir beside the search's checkpoint dump (no-op without one)."""
    from dslabs_tpu.tpu import checkpoint as ckpt_mod

    return setup(ckpt_mod.default_compile_cache_dir(checkpoint_path))
