"""Checkable fault scenarios: declarative fault models compiled into
the tensor event space (ISSUE 19, ROADMAP #5b).

The chaos harness (tpu/chaos.py) injects faults into the ENGINE's
dispatch stream — SIGKILL, OOM, wedges — and proves the checker
recovers.  This module is the other plane: faults of the CHECKED
SYSTEM, declared on the spec and explored exhaustively like any other
model event.  A :class:`FaultModel` on a
:class:`~dslabs_tpu.tpu.compiler.ProtocolSpec` declares

* a network **partition** schedule over node groups — cut and heal are
  model events, budgeted by ``max_eras``;
* **crash/restart** of declared node kinds with a durable-vs-volatile
  field split — crash wipes every non-durable field back to its init
  value and marks the node down (no handler or timer runs, no message
  is deliverable to it) until a restart event;
* bounded message **drop** (removes an in-flight message from the
  network set) and **dup** (tags a bounded re-delivery — the set
  semantics already deliver without consuming, so duplication is
  subsumed behaviorally; the explicit event makes it *nameable* in
  witness traces and *bounded* in the counter lane).

Compilation (tpu/compiler.py) appends one hidden controller node kind
(``$fault``) whose bounded :class:`~dslabs_tpu.tpu.compiler.Field`
lanes carry the partition flag, era/crash/drop/dup counters, and
per-node down flags.  Because fault state is ordinary declared-domain
node lanes, bit-packing, symmetry canonicalization, the spill tier,
and checkpoints carry it with ZERO engine special-casing; the only
engine additions are a third event segment in the enumeration grid and
a deliverability mask (cross-cut and down-destination messages, down
timers), both gated at trace time on ``protocol.fault is not None`` so
a fault-free spec lowers to the byte-identical pre-fault program.

Flat event grid numbering (what traces record):
``[0, net_cap)`` message deliveries, ``[net_cap, net_cap + NN*T_CAP)``
timer fires, then the fault segment::

    CUT, HEAL,                      # iff partition declared
    CRASH(n) for n in crashable,    # iff crash declared
    RESTART(n) for n in crashable,
    DROP(slot) for slot in net,     # iff max_drops > 0
    DUP(slot) for slot in net,      # iff max_dups > 0

Soundness of the deliverability mask is argued in docs/scenarios.md:
masking is *state-dependent pruning of enabled events*, identical in
kind to ``deliver_message`` settings masks — every interleaving of the
budgeted fault events with protocol events is enumerated, and a
message blocked by a cut or a down node stays in the network set,
deliverable again after HEAL/RESTART (messages are never silently
consumed by a fault; only DROP removes, and DROP is itself a recorded
model event)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Partition", "Crash", "FaultModel", "FaultLanes",
           "FAULT_KIND", "FAULT_FIELDS", "controller_kind",
           "compile_fault_lanes"]

# Reserved hidden node kind that carries the fault lanes.  User specs
# may not declare it; handlers may not read it (conformance rule C6).
FAULT_KIND = "$fault"

# Reserved controller field names (C6 flags handler references).
FAULT_FIELDS = ("pcut", "eras", "crashes", "drops", "dups")


@dataclasses.dataclass(frozen=True)
class Partition:
    """A partition schedule over node groups.  ``blocks`` is a tuple of
    blocks; each block is a tuple of entries — a node kind name (every
    instance) or ``(kind, idx)``.  Nodes in different blocks cannot
    exchange messages while the cut is up.  Unlisted nodes are in no
    block and are never cut off.  ``max_eras`` budgets how many times
    the cut may be raised (one era = one CUT; HEAL ends it);
    ``initial_cut`` starts the search already cut (consumes era 1)."""

    blocks: Tuple[tuple, ...]
    max_eras: int = 1
    initial_cut: bool = False


@dataclasses.dataclass(frozen=True)
class Crash:
    """Crash/restart for the kinds named in ``durable``: kind name ->
    tuple of DURABLE field names (survive a crash; every other field
    of the kind is volatile and resets to its declared init).  Pending
    timers of a down node are masked, not cleared — they fire only
    after restart, modelling a recovered node's stale timers.
    ``max_crashes`` budgets total crash events across all nodes."""

    durable: Dict[str, Tuple[str, ...]]
    max_crashes: int = 1


@dataclasses.dataclass(frozen=True)
class FaultModel:
    """The declarative fault plane of a spec (``ProtocolSpec(...,
    fault=FaultModel(...))``).  Any combination of the three fault
    families; zero budgets are legal (a zero-budget model adds
    constant lanes and no valid fault events — the fault-free parity
    oracle the scenario tests pin)."""

    partition: Optional[Partition] = None
    crash: Optional[Crash] = None
    max_drops: int = 0
    max_dups: int = 0


def controller_kind(model: "FaultModel", nodes) -> object:
    """The hidden ``$fault`` NodeKind for ``model`` given the USER node
    kinds (compiler.NodeKind list, pre-append).  All lanes are bounded
    Fields, so packing/symmetry/spill/checkpoints carry them as
    ordinary declared-domain lanes."""
    from dslabs_tpu.tpu.compiler import Field, NodeKind

    fields = []
    if model.partition is not None:
        cut0 = 1 if model.partition.initial_cut else 0
        fields.append(Field("pcut", init=cut0, hi=1))
        fields.append(Field("eras", init=cut0,
                            hi=max(model.partition.max_eras, cut0)))
    if model.crash is not None:
        for k in nodes:
            if k.name in model.crash.durable:
                fields.append(Field(f"down_{k.name}", size=k.count,
                                    hi=1, index_group=k.name))
        fields.append(Field("crashes", hi=model.crash.max_crashes))
    if model.max_drops > 0:
        fields.append(Field("drops", hi=model.max_drops))
    if model.max_dups > 0:
        fields.append(Field("dups", hi=model.max_dups))
    return NodeKind(FAULT_KIND, 1, tuple(fields))


@dataclasses.dataclass(frozen=True)
class FaultLanes:
    """The compiled static descriptor the engine consumes
    (``TensorProtocol.fault``): lane offsets of the controller fields,
    per-node block ids / down-flag offsets / volatile wipe masks, the
    fault event-segment layout, and the budgets.  Everything here is
    host-side numpy/int — the engine turns it into traced one-hot
    selects; nothing is protocol state."""

    model: FaultModel
    n_nodes: int                  # INCLUDING the controller
    node_width: int
    net_cap: int
    # Scalar controller lane offsets (-1 = family absent).
    pcut_off: int
    eras_off: int
    crashes_off: int
    drops_off: int
    dups_off: int
    block_id: np.ndarray          # [n_nodes] int32, -1 = unpartitioned
    down_off: np.ndarray          # [n_nodes] int32, -1 = not crashable
    crash_nodes: np.ndarray       # [nc] int32 node indices
    crash_labels: Tuple[str, ...]  # aligned with crash_nodes
    wipe: np.ndarray              # [nc, node_width] bool (volatile)
    init_vec: np.ndarray          # [node_width] int32

    # ------------------------------------------------ event segment

    @property
    def has_partition(self) -> bool:
        return self.model.partition is not None

    @property
    def n_crashable(self) -> int:
        return int(len(self.crash_nodes))

    @property
    def seg_cut(self) -> int:
        return 0

    @property
    def seg_heal(self) -> int:
        return 1

    @property
    def seg_crash(self) -> int:
        return 2 if self.has_partition else 0

    @property
    def seg_restart(self) -> int:
        return self.seg_crash + self.n_crashable

    @property
    def seg_drop(self) -> int:
        return self.seg_restart + self.n_crashable

    @property
    def seg_dup(self) -> int:
        return self.seg_drop + (self.net_cap
                                if self.model.max_drops > 0 else 0)

    @property
    def n_events(self) -> int:
        return self.seg_dup + (self.net_cap
                               if self.model.max_dups > 0 else 0)

    def event_label(self, f_idx: int) -> str:
        """Human name of fault event ``f_idx`` (trace decoding)."""
        f = int(f_idx)
        if self.has_partition and f == self.seg_cut:
            return "CUT"
        if self.has_partition and f == self.seg_heal:
            return "HEAL"
        nc = self.n_crashable
        if self.seg_crash <= f < self.seg_crash + nc:
            return f"CRASH({self.crash_labels[f - self.seg_crash]})"
        if self.seg_restart <= f < self.seg_restart + nc:
            return f"RESTART({self.crash_labels[f - self.seg_restart]})"
        if (self.model.max_drops > 0
                and self.seg_drop <= f < self.seg_drop + self.net_cap):
            return f"DROP({f - self.seg_drop})"
        if (self.model.max_dups > 0
                and self.seg_dup <= f < self.seg_dup + self.net_cap):
            return f"DUP({f - self.seg_dup})"
        raise IndexError(f"fault event {f} out of range "
                         f"[0, {self.n_events})")

    def signature(self) -> str:
        """Stable identity string joined into checkpoint fingerprints
        (tpu/checkpoint.py): two searches whose fault models differ
        must refuse each other's dumps loudly."""
        m = self.model
        part = None
        if m.partition is not None:
            part = (tuple(tuple(b) for b in m.partition.blocks),
                    m.partition.max_eras, m.partition.initial_cut)
        crash = None
        if m.crash is not None:
            crash = (tuple(sorted(
                (k, tuple(v)) for k, v in m.crash.durable.items())),
                m.crash.max_crashes)
        return repr(("fault-v1", part, crash, m.max_drops, m.max_dups,
                     self.n_nodes, self.net_cap))


def compile_fault_lanes(spec, table, node_width: int,
                        init_vec: np.ndarray) -> FaultLanes:
    """Build the :class:`FaultLanes` descriptor for ``spec`` (whose
    node list ALREADY includes the appended ``$fault`` controller).
    ``table`` is the spec's ``_layout()`` table; ``init_vec`` the full
    node-lane init vector.  Structural validation lives in
    ``ProtocolSpec.validate`` — this assumes a validated spec."""
    model = spec.fault
    n_nodes = sum(k.count for k in spec.nodes)
    user_nodes = [k for k in spec.nodes if k.name != FAULT_KIND]

    def _scalar_off(fname: str) -> int:
        key = (FAULT_KIND, 0, fname)
        return table[key][0] if key in table else -1

    block_id = np.full((n_nodes,), -1, np.int32)
    if model.partition is not None:
        for b, block in enumerate(model.partition.blocks):
            for entry in block:
                if isinstance(entry, str):
                    kind = next(k for k in user_nodes
                                if k.name == entry)
                    for i in range(kind.count):
                        block_id[spec._node_index(entry, i)] = b
                else:
                    kind_name, idx = entry
                    block_id[spec._node_index(kind_name, idx)] = b

    down_off = np.full((n_nodes,), -1, np.int32)
    crash_nodes = []
    crash_labels = []
    wipe_rows = []
    if model.crash is not None:
        for kind in user_nodes:
            if kind.name not in model.crash.durable:
                continue
            durable = set(model.crash.durable[kind.name])
            base_off = table[(FAULT_KIND, 0, f"down_{kind.name}")][0]
            for i in range(kind.count):
                n = spec._node_index(kind.name, i)
                down_off[n] = base_off + i
                crash_nodes.append(n)
                crash_labels.append(f"{kind.name}[{i}]")
                w = np.zeros((node_width,), bool)
                for f in kind.fields:
                    if f.name in durable:
                        continue
                    off, size = table[(kind.name, i, f.name)]
                    w[off:off + size] = True
                wipe_rows.append(w)

    return FaultLanes(
        model=model,
        n_nodes=n_nodes,
        node_width=node_width,
        net_cap=spec.net_cap,
        pcut_off=_scalar_off("pcut"),
        eras_off=_scalar_off("eras"),
        crashes_off=_scalar_off("crashes"),
        drops_off=_scalar_off("drops"),
        dups_off=_scalar_off("dups"),
        block_id=block_id,
        down_off=down_off,
        crash_nodes=np.asarray(crash_nodes, np.int32),
        crash_labels=tuple(crash_labels),
        wipe=(np.stack(wipe_rows) if wipe_rows
              else np.zeros((0, node_width), bool)),
        init_vec=np.asarray(init_vec, np.int32),
    )


def validate_fault(spec) -> None:
    """Fault-model structural hygiene, raised as structured SpecError
    at the compile gate (the C4/C5 discipline extended to the fault
    plane).  ``spec.nodes`` already includes the controller kind."""
    from dslabs_tpu.tpu.compiler import SpecError

    model = spec.fault
    user_nodes = [k for k in spec.nodes if k.name != FAULT_KIND]
    kind_by_name = {k.name: k for k in user_nodes}

    if model.max_drops < 0 or model.max_dups < 0:
        raise SpecError(
            f"fault budgets must be >= 0 (max_drops={model.max_drops}, "
            f"max_dups={model.max_dups})", spec=spec.name)

    part = model.partition
    if part is not None:
        if len(part.blocks) < 2:
            raise SpecError(
                "partition needs >= 2 blocks (a single block cuts "
                "nothing)", spec=spec.name)
        if part.max_eras < 0:
            raise SpecError(
                f"partition max_eras must be >= 0 (got "
                f"{part.max_eras})", spec=spec.name)
        if part.initial_cut and part.max_eras < 1:
            raise SpecError(
                "initial_cut consumes partition era 1 — max_eras must "
                "be >= 1", spec=spec.name)
        seen = {}
        for b, block in enumerate(part.blocks):
            for entry in block:
                if isinstance(entry, str):
                    kind_name, idxs = entry, None
                else:
                    try:
                        kind_name, idx = entry
                        idxs = (idx,)
                    except (TypeError, ValueError):
                        raise SpecError(
                            f"partition block entry {entry!r} is "
                            "neither a kind name nor (kind, idx)",
                            spec=spec.name)
                kind = kind_by_name.get(kind_name)
                if kind is None:
                    raise SpecError(
                        f"partition block names unknown node kind "
                        f"{kind_name!r} (declared: "
                        f"{sorted(kind_by_name)})",
                        spec=spec.name, kind=kind_name)
                if idxs is None:
                    idxs = range(kind.count)
                for i in idxs:
                    if not (0 <= i < kind.count):
                        raise SpecError(
                            f"partition block entry ({kind_name!r}, "
                            f"{i}) out of range (kind has "
                            f"{kind.count} instances)",
                            spec=spec.name, kind=kind_name)
                    key = (kind_name, i)
                    if key in seen and seen[key] != b:
                        raise SpecError(
                            f"node ({kind_name!r}, {i}) appears in "
                            f"partition blocks {seen[key]} and {b}",
                            spec=spec.name, kind=kind_name)
                    seen[key] = b
        # Symmetry soundness: a declared-interchangeable kind must not
        # be SPLIT across blocks (canonical relabeling would move a
        # node across the cut).  Whole-kind membership is fine.
        for g in spec.symmetry:
            kind = kind_by_name.get(g)
            if kind is None:
                continue
            ids = {seen.get((g, i), -1) for i in range(kind.count)}
            if len(ids) > 1:
                raise SpecError(
                    f"partition blocks split symmetry group {g!r} "
                    f"across blocks {sorted(ids)} — interchangeable "
                    "instances must share one block (or none)",
                    spec=spec.name, kind=g, code="C5")

    crash = model.crash
    if crash is not None:
        if crash.max_crashes < 0:
            raise SpecError(
                f"crash max_crashes must be >= 0 (got "
                f"{crash.max_crashes})", spec=spec.name)
        for kind_name, durable in crash.durable.items():
            kind = kind_by_name.get(kind_name)
            if kind is None:
                raise SpecError(
                    f"crash durable names unknown node kind "
                    f"{kind_name!r} (declared: "
                    f"{sorted(kind_by_name)})",
                    spec=spec.name, kind=kind_name)
            declared = {f.name for f in kind.fields}
            for fname in durable:
                if fname not in declared:
                    raise SpecError(
                        f"crash durable field {fname!r} not declared "
                        f"on kind {kind_name!r} (declared: "
                        f"{sorted(declared)})",
                        spec=spec.name, kind=kind_name, field=fname)
