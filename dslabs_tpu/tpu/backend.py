"""The tensor engine as a harness-selectable search strategy.

SURVEY §8.1: "a ``Search``/``SearchSettings``-shaped plugin point; the TPU
backend is a new ``Search`` strategy selectable by settings" (reference
entry points ``Search.bfs/dfs``, Search.java:390-402).  This module is
that plugin point: :func:`tensor_bfs` accepts the SAME object
``SearchState`` + ``SearchSettings`` the lab search tests build, runs the
search on the TPU tensor engine via the lab's protocol twin, and returns
an object ``SearchResults`` whose terminal states are REAL object states
(reconstructed by trace replay on the object twin, tpu/trace.py) — so
staged searches (``results.goal_matching_state`` fed into the next
``bfs``) and trace assertions keep working unchanged.

Pipeline per call:

1. **Twin resolution** — registered :class:`TwinAdapter`\\ s inspect the
   object state's node composition and return a :class:`TwinBinding`
   (tensor protocol + address/command maps + lane predicates).  No twin =
   loud :class:`NoTensorTwin`, never a silent object-path fallback.
2. **Root derivation** — a depth-0 canonical state maps to the twin's
   initial state.  A STAGED state (a goal state from a previous
   tensor-backend phase) carries a :class:`TensorProvenance` history
   (event ids + staged ops like dropPendingMessages); the tensor root is
   re-derived by replaying that history through the twin's transition,
   the exact inverse of how the object state itself was materialised.
3. **Settings compilation** — the link matrix / sender / receiver /
   network flags become a [NN, NN] delivery matrix (the twin's
   ``deliver_message`` mask), per-node timer gating a [NN] vector, and
   every invariant/goal/prune ``StatePredicate`` is translated to a lane
   predicate via its ``tkey`` metadata (combinators translate
   structurally).  Untranslatable predicate = loud NoTensorTwin.
4. **Run** — ShardedTensorSearch, strict=True (drops are fatal: lab
   verdicts must be exact), record_trace=True; capacity ladder retries
   CapacityOverflow with doubled caps (no hand-tuned budgets).
5. **Results adaptation** — end conditions map onto the object
   ``EndCondition`` (the object checker treats the depth limit as a
   prune, so tensor DEPTH_EXHAUSTED reports SPACE_EXHAUSTED); terminal
   tensor states are replayed onto the object twin and re-checked with
   the ORIGINAL object predicate — a twin/object verdict divergence
   raises instead of returning a wrong answer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["NoTensorTwin", "TensorProvenance", "TwinBinding",
           "register_adapter", "tensor_bfs", "tensor_dfs"]


class NoTensorTwin(RuntimeError):
    """No tensor twin / translation exists for this search configuration.

    Raised loudly (the test errors) rather than silently falling back to
    the object checker: ``--search-backend tensor`` must mean the tensor
    engine actually ran the search."""


@dataclasses.dataclass
class TensorProvenance:
    """How a staged object state was produced, in twin terms: the binding
    config it belongs to and the ordered history of events and staged ops
    (``("ev_msg", net_slot)``, ``("ev_tmr", node, queue_slot)``,
    ``("drop",)``, ``("undrop_from", name)``, ``("undrop_to", name)``,
    ``("undrop_all",)``) from the twin's initial state.  Events are
    recorded CAP-INDEPENDENTLY — canonical network packing keeps occupied
    slot indices identical across any net_cap >= occupancy, and timer
    (node, queue-slot) pairs do not reference the grid stride — so the
    history replays identically under a different capacity-ladder rung
    than the one that recorded it.  Lets the next search phase re-derive
    the tensor root without an object->tensor state encoder."""

    key: tuple
    history: List[tuple] = dataclasses.field(default_factory=list)


def _norm_event(p, ev: int) -> tuple:
    """Grid event id (relative to protocol p's caps) -> cap-independent
    provenance op."""
    if ev < p.net_cap:
        return ("ev_msg", int(ev))
    t = ev - p.net_cap
    return ("ev_tmr", int(t) // p.timer_cap, int(t) % p.timer_cap)


def _denorm_event(p, op: tuple) -> int:
    # Capacity misses here are ladder-retryable, not twin-missing: a
    # history recorded by a phase that escalated the capacity ladder can
    # reference slots beyond a lower rung's caps (ADVICE r4).  Lazy
    # import (like every jax-adjacent import in this module) so the
    # object-only path never pays the engine import.
    from dslabs_tpu.tpu.engine import CapacityOverflow

    if op[0] == "ev_msg":
        if op[1] >= p.net_cap:
            raise CapacityOverflow(
                f"provenance slot {op[1]} beyond net_cap {p.net_cap}")
        return op[1]
    if op[2] >= p.timer_cap:
        raise CapacityOverflow(
            f"provenance timer slot {op[2]} beyond timer_cap "
            f"{p.timer_cap}")
    return p.net_cap + op[1] * p.timer_cap + op[2]


class TwinBinding:
    """A resolved (object configuration -> tensor twin) binding.

    Subclasses (one per lab family, see tpu/adapters/) provide:

    - ``key``: hashable config identity (stable across staged phases)
    - ``build_protocol(net_cap, timer_cap) -> TensorProtocol`` (no masks)
    - ``addr_index``: root-address name -> twin node index
    - ``predicate(tkey) -> fn(state_slice) -> bool`` lane predicate
    - ``initial_caps() -> (net_cap, timer_cap)`` starting capacities
    """

    key: tuple = ()
    addr_index: Dict[str, int] = {}

    def build_protocol(self, net_cap: int, timer_cap: int):
        raise NotImplementedError

    def initial_caps(self) -> Tuple[int, int]:
        raise NotImplementedError

    def predicate(self, tkey) -> Callable:
        raise NotImplementedError

    def check_settings(self, settings) -> None:
        """Hook: raise NoTensorTwin when the settings demand events the
        twin does not model (e.g. live timers on an unmodeled node).
        Bindings whose twins model every node's full event surface can
        keep the default no-op."""

    def derive_root(self, search, state):
        """Hook: object initial/staged state -> (tensor root pytree or
        None for the twin initial, provenance history).  Default = the
        module-level provenance replay; bindings whose twin initial
        state BAKES IN a staged prefix (lab 4's joined root) override
        with validation-based mapping."""
        return derive_root(self, search, state)

    def msg_mask_fn(self) -> Callable:
        """fn(msg_record, [NN*NN] link matrix) -> deliverable, for the
        default [tag, frm, to, ...] record layout; bindings whose twins
        do not carry frm/to lanes (e.g. lab 1's [tag, c, s]) override
        with their own lane mapping."""
        nn = len(self.addr_index)

        def fn(msg, marr, nn=nn):
            import jax.numpy as jnp

            k = (msg[1].clip(0, nn - 1) * nn
                 + msg[2].clip(0, nn - 1))
            return jnp.sum(jnp.where(jnp.arange(nn * nn) == k, marr,
                                     False))
        return fn

    @staticmethod
    def tmr_mask_fn(nn: int) -> Callable:
        def fn(node, tarr, nn=nn):
            import jax.numpy as jnp

            return jnp.sum(jnp.where(jnp.arange(nn) == node, tarr,
                                     False))
        return fn


_ADAPTERS: List[Callable] = []


def register_adapter(fn: Callable) -> Callable:
    """Register ``fn(object_state) -> Optional[TwinBinding]``."""
    _ADAPTERS.append(fn)
    return fn


def _load_adapters() -> None:
    # Import for registration side effects; lazy to avoid jax import cost
    # on the object path.
    from dslabs_tpu.tpu.adapters import paxos as _p  # noqa: F401
    from dslabs_tpu.tpu.adapters import shardstore as _ss  # noqa: F401
    from dslabs_tpu.tpu.adapters import simple as _s  # noqa: F401


def resolve_binding(state) -> TwinBinding:
    _load_adapters()
    for fn in _ADAPTERS:
        b = fn(state)
        if b is not None:
            return b
    kinds = sorted({type(n).__name__ for n in state.nodes()})
    raise NoTensorTwin(
        f"no tensor twin adapter matches node composition {kinds} — "
        "the tensor search backend only covers protocols with registered "
        "twins (tpu/adapters/)")


# ------------------------------------------------------------ predicates

def translate_predicate(binding: TwinBinding, pred) -> Callable:
    """Object StatePredicate -> twin lane predicate, recursing through
    combinator structure; loud NoTensorTwin when untranslatable."""
    import jax.numpy as jnp

    st = getattr(pred, "structure", None)
    if st is not None:
        op = st[0]
        subs = [translate_predicate(binding, q) for q in st[1:]]
        if op == "not":
            return lambda s, f=subs[0]: ~f(s)
        if op == "and":
            return lambda s, a=subs[0], b=subs[1]: a(s) & b(s)
        if op == "or":
            return lambda s, a=subs[0], b=subs[1]: a(s) | b(s)
        if op == "implies":
            return lambda s, a=subs[0], b=subs[1]: ~a(s) | b(s)
    tkey = getattr(pred, "tkey", None)
    if tkey is None:
        raise NoTensorTwin(
            f"predicate {pred.name!r} has no tensor translation key and "
            "no combinator structure")
    fn = binding.predicate(tkey)
    if fn is None:
        raise NoTensorTwin(
            f"binding {binding.key} cannot translate predicate "
            f"{pred.name!r} (tkey {tkey!r})")
    return fn


# -------------------------------------------------------------- settings

def _addr_name(a) -> str:
    return str(a.root_address())


def compile_masks(binding: TwinBinding, settings):
    """TestSettings network/timer gating -> ([NN*NN] link matrix,
    [NN] timer vector) bool arrays.  The matrix reproduces
    TestSettings.should_deliver's precedence exactly: link override ->
    sender -> receiver -> network_active (testing/settings.py:138-151).
    The arrays are passed to the jitted programs as RUNTIME arguments
    (engine deliver_*_rt) so staged phases never recompile; lookups are
    one-hot select-reduces, never traced-index gathers (the measured
    ~1 GB/s pathology under the flat vmap)."""
    idx = binding.addr_index
    nn = len(idx)
    names = {i: a for a, i in idx.items()}
    mat = np.zeros((nn, nn), dtype=bool)
    link = {(_addr_name(f), _addr_name(t)): v
            for (f, t), v in settings._link_active.items()}
    snd = {_addr_name(a): v for a, v in settings._sender_active.items()}
    rcv = {_addr_name(a): v for a, v in settings._receiver_active.items()}
    for fi in range(nn):
        for ti in range(nn):
            f, t = names[fi], names[ti]
            v = link.get((f, t))
            if v is None:
                v = snd.get(f)
            if v is None:
                v = rcv.get(t)
            if v is None:
                v = settings._network_active
            mat[fi, ti] = v
    from dslabs_tpu.core.address import LocalAddress

    tvec = np.array(
        [settings.should_deliver_timer(LocalAddress(names[i]))
         for i in range(nn)], dtype=bool)
    return mat.reshape(-1), tvec



# ------------------------------------------------------------ state root

def derive_root(binding: TwinBinding, search, state):
    """Object initial state -> (tensor root pytree or None for the twin
    initial, provenance history list).  Depth-0 canonical states map to
    the twin initial; staged states replay their provenance history."""
    import jax
    import jax.numpy as jnp

    from dslabs_tpu.tpu.engine import (CapacityOverflow, SENTINEL,
                                       flatten_state)

    prov = getattr(state, "_tensor_provenance", None)
    if prov is None:
        if state.depth != 0:
            raise NoTensorTwin(
                "staged search from a state with no tensor provenance "
                "(depth {}) — only states produced by a previous "
                "tensor-backend phase can seed a new phase".format(
                    state.depth))
        # Pre-search staged mutations on the pristine state (e.g.
        # drop_pending_messages before the first bfs) are recorded on
        # the instance and replayed like any provenance history.
        staged = list(getattr(state, "_staged_ops", []))
        prov = TensorProvenance(binding.key, staged)
        if not staged:
            return None, []
    if prov.key != binding.key:
        raise NoTensorTwin(
            f"staged state's provenance {prov.key} does not match the "
            f"current binding {binding.key}")
    row_state = search.initial_state()
    row = np.asarray(flatten_state(row_state))[0]
    # Replay UNMASKED: the history's events were valid under the masks
    # of the phases that produced them, not under THIS phase's masks
    # (e.g. a deliver_timers(False) phase 3 must still replay phase 1's
    # election timers).  Masks only gate validity, never the transition,
    # so unmasked replay reproduces each original successor exactly.
    p = dataclasses.replace(search.p, deliver_message=None,
                            deliver_timer=None)
    from dslabs_tpu.tpu.engine import TensorSearch as _TS

    replayer = _TS(p, chunk=1)
    step = jax.jit(replayer._step_one)
    o0, o1 = search._off[0], search._off[1]
    dropped: List[np.ndarray] = []
    for op in prov.history:
        if op[0] in ("ev_msg", "ev_tmr"):
            ev = _denorm_event(p, op)
            succ, valid, over = step(jnp.asarray(row), jnp.asarray(ev))
            if int(over):
                # The replayed transition itself overflowed this rung's
                # net/timer caps — a truncated root would corrupt every
                # downstream verdict, so escalate the ladder instead.
                raise CapacityOverflow(
                    f"provenance replay of {op!r} overflowed caps "
                    f"(net_cap={p.net_cap}, timer_cap={p.timer_cap})")
            if not bool(valid):
                raise NoTensorTwin(
                    f"provenance replay hit undeliverable event {op!r}")
            row = np.asarray(succ)
        elif op[0] == "drop":
            net = row[o0:o1].reshape(p.net_cap, p.msg_width)
            dropped.extend(r.copy() for r in net if r[0] != SENTINEL)
            row = row.copy()
            row[o0:o1] = SENTINEL
        elif op[0].startswith("undrop"):
            net = row[o0:o1].reshape(p.net_cap, p.msg_width).copy()
            want = (binding.addr_index[op[1]] if len(op) > 1 else None)
            back = []
            for r in dropped:
                if op[0] == "undrop_from" and int(r[1]) != want:
                    continue
                if op[0] == "undrop_to" and int(r[2]) != want:
                    continue
                back.append(r)
            have = [r for r in net if r[0] != SENTINEL]
            merged = {tuple(r) for r in have} | {tuple(r) for r in back}
            rows = sorted(merged)
            if len(rows) > p.net_cap:
                raise CapacityOverflow(
                    f"undrop needs {len(rows)} net slots > cap "
                    f"{p.net_cap}")
            net[:] = SENTINEL
            for i, r in enumerate(rows):
                net[i] = r
            row = row.copy()
            row[o0:o1] = net.reshape(-1)
        else:
            raise NoTensorTwin(f"unknown staged op {op!r}")
    return search.unflatten_rows(jnp.asarray(row[None])), list(prov.history)


# ------------------------------------------------------------------- run

# Capacity escalation ladder: (frontier_cap, visited_cap) per attempt,
# with net/timer caps doubling alongside.  No hand-tuned budgets: every
# CapacityOverflow retries one rung up, and the last failure is loud.
_LADDER = [(1 << 14, 1 << 19), (1 << 17, 1 << 22), (1 << 19, 1 << 24)]


def _run_tensor(binding: TwinBinding, settings, state, chunk=512):
    import jax

    from dslabs_tpu.tpu.engine import CapacityOverflow
    from dslabs_tpu.tpu.sharded import ShardedTensorSearch, make_mesh

    net_cap, timer_cap = binding.initial_caps()
    mesh = make_mesh(len(jax.devices()))
    last: Optional[Exception] = None
    # check_settings BEFORE build_protocol: bindings bind settings-
    # dependent modelling flags there (lab4's live-master-timer /
    # controller-debris surface) and the protocol shape must reflect
    # them on the FIRST attempt, not after a capacity retry.
    binding.check_settings(settings)
    for attempt, (f_cap, v_cap) in enumerate(_LADDER):
        protocol, marr, tarr = _bind_protocol(
            binding, settings, net_cap << attempt,
            timer_cap + 2 * attempt)
        search = ShardedTensorSearch(
            protocol, mesh, chunk_per_device=chunk, frontier_cap=f_cap,
            visited_cap=v_cap, strict=True, record_trace=True)
        # Transient-dispatch retry (tpu/supervisor.py): a preemption or
        # transient XLA error mid-search retries with backoff instead of
        # failing the lab test; verdict flow is untouched (semantic
        # errors like CapacityOverflow pass straight through to the
        # capacity ladder below).
        from dslabs_tpu.tpu.supervisor import install_retry

        install_retry(search)
        search.set_runtime_masks(marr, tarr)
        rel = None
        if settings.depth_limited():
            rel = settings.max_depth - state.depth
            if rel < 0:
                raise NoTensorTwin("staged state already beyond max_depth")
        try:
            # Inside the attempt: a root recorded by a phase that ran at
            # a higher ladder rung can overflow this rung's caps, and
            # must escalate rather than fail the test (ADVICE r4).
            root, history = binding.derive_root(search, state)
            if settings.max_time_secs is not None and (
                    rel is None or rel > 2):
                # Warm-up excludes compile time from the test's time
                # budget (the reference charges neither JIT nor class
                # loading to maxTime; on the accelerator a cold twin
                # compile alone can exceed a 30 s search budget).  A
                # phase within 2 levels of its depth limit skips it —
                # the warm-up WOULD BE the whole search.
                search.max_depth = 2
                search.run(initial=root, check_initial=False)
            search.max_depth = rel
            if settings.max_time_secs is not None:
                from dslabs_tpu.utils.flags import GlobalSettings

                search.max_secs = (settings.max_time_secs
                                   * GlobalSettings.time_scale)
            else:
                search.max_secs = None
            outcome = search.run(initial=root)
            return search, outcome, history
        except CapacityOverflow as e:
            last = e
            continue
    raise last


def _materialize(binding, search, outcome, state, history):
    """Tensor terminal state -> object SearchState via trace replay, with
    provenance attached for the next staged phase."""
    from dslabs_tpu.tpu.trace import replay_on_object

    obj = replay_on_object(search, outcome, state)
    obj._tensor_provenance = TensorProvenance(
        binding.key, list(history) + [_norm_event(search.p, e)
                                      for e in outcome.trace])
    return obj


def _sampled_value_recheck(binding, search, outcome, settings, state):
    """Value-level invariants (RESULTS_OK and friends) collapse to
    constant-true lane predicates on the twin, so the tensor search can
    never falsify them mid-run; before an exhaust verdict is trusted,
    replay the outcome's sampled deepest states on the OBJECT twin and
    check every value-level invariant there (ADVICE r4).  Returns the
    first violated ``(object_state, predicate, result)`` or ``None``."""
    if not outcome.samples:
        return None
    value_preds = [p for p in settings.invariants
                   if getattr(translate_predicate(binding, p),
                              "value_level", False)]
    if not value_preds:
        return None
    from dslabs_tpu.tpu.trace import replay_on_object

    for tr in outcome.samples:
        shim = dataclasses.replace(outcome, trace=list(tr))
        obj = replay_on_object(search, shim, state)
        for p in value_preds:
            r = p.check(obj)
            if not r.value:
                return obj, p, r
    return None


def _bind_protocol(binding, settings, net_cap, timer_cap,
                   with_goals=True):
    """Assemble the runnable twin for one capacity rung: protocol with
    translated predicates + runtime mask arrays — ONE code path for the
    BFS ladder and the rollout probe, so both always search identically
    configured twins."""
    marr, tarr = compile_masks(binding, settings)
    protocol = binding.build_protocol(net_cap, timer_cap)
    inv = {p.name: translate_predicate(binding, p)
           for p in settings.invariants}
    goals = ({p.name: translate_predicate(binding, p)
              for p in settings.goals} if with_goals else {})
    prunes = {p.name: translate_predicate(binding, p)
              for p in settings.prunes}
    protocol = dataclasses.replace(
        protocol, invariants=inv, goals=goals, prunes=prunes,
        deliver_message_rt=binding.msg_mask_fn(),
        deliver_timer_rt=TwinBinding.tmr_mask_fn(len(tarr)))
    return protocol, marr, tarr


def _rollout_probe(binding, settings, state):
    """Swarm deep probe before a dfs-routed BFS: a diversified
    random-walk fleet (tpu/swarm.py ``SwarmSearch`` — ONE walker
    implementation; the ad-hoc per-backend rollout loop is retired)
    reaches depth d in O(d) steps, so the deep-narrow violations the
    object RandomDFS could hit inside a time budget are covered BEFORE
    the level-by-level search starts.  This function keeps only the
    BUDGET ACCOUNTING — walker mechanics, dedup, overflow-restart
    counting, and the minimize/replay witness pipeline all live in the
    swarm subsystem.  Returns ((search, outcome, history), probe_secs)
    on a terminal hit, else (None, probe_secs) — capacity overflows
    skip the probe (the BFS ladder owns caps)."""
    import time

    import jax

    from dslabs_tpu.tpu.engine import CapacityOverflow
    from dslabs_tpu.tpu.sharded import make_mesh
    from dslabs_tpu.tpu.swarm import SwarmSearch
    from dslabs_tpu.utils.flags import GlobalSettings

    t_probe = time.time()
    try:
        binding.check_settings(settings)
        net_cap, timer_cap = binding.initial_caps()
        # Probe at the capacity ladder's TOP rung outright: walkers
        # hold K rows, not a frontier, so the wide caps cost nothing —
        # and at base caps every truncated step would restart a walker
        # below the very depths the probe exists to reach (the
        # truncation count is loud now: SearchOutcome.swarm_overflow).
        top = len(_LADDER) - 1
        protocol, marr, tarr = _bind_protocol(
            binding, settings, net_cap << top, timer_cap + 2 * top,
            with_goals=False)
        rel = (settings.max_depth - state.depth
               if settings.depth_limited() else 192)
        if rel <= 0:
            return None, time.time() - t_probe
        search = SwarmSearch(protocol, mesh=make_mesh(1),
                             walkers_per_device=128,
                             max_steps=min(rel, 192), seed=0)
        from dslabs_tpu.tpu.supervisor import install_retry

        install_retry(search)
        search.set_runtime_masks(marr, tarr)
        root, history = binding.derive_root(search, state)
        budget = 10.0 * GlobalSettings.time_scale
        if settings.max_time_secs is not None:
            budget = min(budget, settings.max_time_secs / 3
                         * GlobalSettings.time_scale)
        search.max_secs = budget
        outcome = search.run(
            initial=(jax.tree.map(jax.numpy.asarray, root)
                     if root is not None else None),
            check_initial=False)
    except CapacityOverflow:
        return None, time.time() - t_probe
    if outcome.end_condition in ("INVARIANT_VIOLATED",
                                 "EXCEPTION_THROWN"):
        return (search, outcome, history), time.time() - t_probe
    return None, time.time() - t_probe


def _object_minimize_verify(obj, pred, result):
    """Probe witnesses run the OBJECT pipeline too (ISSUE 5): the
    replayed object state is minimized with search/minimize.py (the
    reference TraceMinimizer discipline) and the minimized event
    history is INDEPENDENTLY replayed with search/replay.py under the
    violated predicate — a probe verdict ships only after the tensor
    witness (already minimized/replay-verified in tpu/swarm.py) is
    confirmed end-to-end on the object twin.  Returns the minimized
    ``(state, predicate_result)``; any divergence is a loud
    NoTensorTwin, never a silently-wrong trace."""
    from dslabs_tpu.search.minimize import minimize_trace
    from dslabs_tpu.search.replay import replay_trace
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings

    mini = minimize_trace(obj, result)
    r2 = pred.check(mini)
    if r2.value:
        raise NoTensorTwin(
            f"object minimization broke the violation of "
            f"{pred.name!r} (minimizer/predicate divergence)")
    events = []
    s = mini
    while s.previous is not None:
        events.insert(0, s.previous_event)
        s = s.previous
    replayed = replay_trace(s, events,
                            SearchSettings().add_invariant(pred))
    if replayed.end_condition is not EndCondition.INVARIANT_VIOLATED:
        raise NoTensorTwin(
            f"replaying the minimized witness did not reproduce the "
            f"violation of {pred.name!r} "
            f"(got {replayed.end_condition})")
    return mini, r2


def tensor_bfs(initial_state, settings=None, _probe_first=False):
    """The tensor-strategy analog of search.bfs (Search.java:390-402 via
    SURVEY §8.1): same inputs, same SearchResults contract."""
    from dslabs_tpu.search.results import EndCondition, SearchResults
    from dslabs_tpu.search.settings import SearchSettings

    settings = settings if settings is not None else SearchSettings()
    binding = resolve_binding(initial_state)
    trip = None
    if _probe_first:
        trip, probe_secs = _rollout_probe(binding, settings,
                                          initial_state)
        if trip is None and settings.max_time_secs is not None:
            # The probe spends part of the SAME maxTime contract the
            # object RandomDFS honours — deduct it from the BFS's
            # budget (on a copy; the caller's settings are theirs).
            import copy as _copy

            settings = _copy.copy(settings)
            settings.max_time_secs = max(
                1.0, settings.max_time_secs - probe_secs)
    if trip is not None:
        search, outcome, history = trip
    else:
        search, outcome, history = _run_tensor(binding, settings,
                                               initial_state)
    results = SearchResults(settings.invariants, settings.goals)
    results.discovered_count = outcome.unique_states
    # Degradation stats ride along so exhaust verdicts are auditable:
    # dropped (beam truncation) and visited_overflow (table-full
    # treat-as-fresh re-exploration) are both 0 on strict runs.
    results.dropped = outcome.dropped
    results.visited_overflow = outcome.visited_overflow
    end = outcome.end_condition
    by_name = {p.name: p for p in (settings.invariants + settings.goals)}
    if end == "GOAL_FOUND":
        obj = _materialize(binding, search, outcome, initial_state,
                           history)
        pred = by_name[outcome.predicate_name]
        r = pred.check(obj)
        if not r.value:
            raise NoTensorTwin(
                f"twin/object divergence: tensor goal "
                f"{outcome.predicate_name!r} does not hold on the "
                "replayed object state")
        results.goal_found(obj, r)
        results.end_condition = EndCondition.GOAL_FOUND
    elif end == "INVARIANT_VIOLATED":
        obj = _materialize(binding, search, outcome, initial_state,
                           history)
        pred = by_name[outcome.predicate_name]
        r = pred.check(obj)
        if r.value:
            raise NoTensorTwin(
                f"twin/object divergence: tensor invariant violation "
                f"{outcome.predicate_name!r} holds on the replayed "
                "object state")
        if trip is not None:
            # Probe (swarm) witnesses: object-level minimize + replay
            # verification on top of the tensor-level pipeline the
            # swarm already ran (outcome.witness).
            obj, r = _object_minimize_verify(obj, pred, r)
            if outcome.witness is not None:
                outcome.witness.object_verified = True
        results.invariant_violated(obj, r)
        results.end_condition = EndCondition.INVARIANT_VIOLATED
    elif end == "EXCEPTION_THROWN":
        obj = _materialize(binding, search, outcome, initial_state,
                           history)
        results.exception_thrown(obj)
        results.end_condition = EndCondition.EXCEPTION_THROWN
    else:
        hit = _sampled_value_recheck(binding, search, outcome, settings,
                                     initial_state)
        if hit is not None:
            obj, pred, r = hit
            results.invariant_violated(obj, r)
            results.end_condition = EndCondition.INVARIANT_VIOLATED
        elif end == "TIME_EXHAUSTED":
            results.end_condition = EndCondition.TIME_EXHAUSTED
        else:
            # SPACE_EXHAUSTED, DEPTH_EXHAUSTED, CAPACITY_EXHAUSTED: the
            # object checker treats the depth limit as a prune and
            # reports SPACE_EXHAUSTED (Search.java:222-229).
            results.end_condition = EndCondition.SPACE_EXHAUSTED
    return results


def tensor_dfs(initial_state, settings=None):
    """Tensor strategy for dfs call sites: a RANDOM-ROLLOUT deep probe
    (engine.random_rollouts — RandomDFS's O(d) depth reach, restoring
    the coverage the round-4 advisor flagged) followed by a strict BFS
    under the same settings.  The probe's violations carry full
    replayable traces through the same materialisation path; when it
    finds nothing, BFS contributes what RandomDFS never could —
    exhaustiveness at every level it completes."""
    return tensor_bfs(initial_state, settings, _probe_first=True)
