"""Fault-tolerant search supervisor: retry, watchdog, engine failover.

The north-star deployment is an hours-long accelerator job, and before
this module ANY transient device error, preemption, or wedged TPU killed
a run outright.  The supervisor gives the framework the same spine a
production training/inference stack assumes:

* **One dispatch boundary.**  Every device dispatch in the hot loops —
  the sharded chunk step / level promote / stats sync (sharded.py), the
  single-device wave step / promote / scalar sync (engine.py
  ``_run_device``), and the host loop's expand — funnels through
  ``TensorSearch._dispatch(tag, fn, *args)``.  With no supervisor
  installed that is a zero-cost passthrough; the supervisor installs a
  :class:`DispatchBoundary` there.
* **Failure classification + bounded retry.**  Transient runtime errors
  (XLA RESOURCE_EXHAUSTED / UNAVAILABLE / ABORTED, preemptions,
  :class:`TransientDeviceError` from the fault harness) retry in place
  with exponential backoff + deterministic jitter up to
  ``RetryPolicy.max_retries``.  Fatal errors and exhausted budgets
  raise :class:`EngineFailure`.
* **Wall-clock watchdog.**  With ``RetryPolicy.deadline_secs`` set,
  each dispatch runs on a watchdog thread; a dispatch exceeding its
  deadline (wedged device) is ABANDONED — :class:`DispatchTimeout`,
  classified wedged, no retry — and the supervisor restarts on the
  next rung from the last checkpoint.  ``bench.py``'s wedged-TPU
  preflight is a thin client (:func:`probe_device`).
* **Engine failover ladder.**  :class:`SearchSupervisor` runs the
  search on the first healthy rung of ``sharded -> device -> host``
  (the host loop is the parity oracle — every rung has identical
  verdict semantics), resuming each rung from the shared
  engine-agnostic checkpoint (tpu/checkpoint.py) when one exists.
  Semantic errors (``CapacityOverflow``, ``CheckpointMismatch``)
  propagate unchanged — failover can never mask a wrong-config verdict.
* **Deterministic fault injection.**  A :class:`FaultPlan` installed at
  the same boundary makes every recovery path exercisable in CI on CPU
  ("dispatch k of engine E raises", "dispatch j hangs") — see
  tests/test_supervisor.py and ``make fault-smoke``.
* **Process isolation.**  The in-process watchdog can only ABANDON a
  wedged dispatch (the blocked daemon thread leaks — counted on
  ``SearchOutcome.abandoned_threads`` and warned about past
  ``DSLABS_ABANDONED_WARN``).  ``SearchSupervisor(
  process_isolation=True, protocol_factory="module:callable")`` runs
  the ladder through the dispatch warden instead (tpu/warden.py): each
  rung is a SPAWNED CHILD heartbeating over a pipe, a silent child is
  SIGKILLed and reaped, and the next rung's child resumes from the
  unified checkpoint — nothing leaks, and a hard runtime wedge cannot
  take the supervising process down.

* **Portfolio mode.**  ``SearchSupervisor(portfolio=True)`` runs the
  device-sharded swarm explorer (tpu/swarm.py) as a CONCURRENT lane
  beside the BFS ladder — the reference's BFS + RandomDFS portfolio
  (SURVEY §2.4) on the accelerator.  The first terminal verdict
  (violation / exception / goal) wins and the losing lane is cancelled
  at its next loop boundary; exhaustive BFS verdicts stay
  authoritative.  Swarm witnesses arrive minimized and
  replay-verified (``SearchOutcome.witness``); swarm rounds
  checkpoint/resume beside the BFS dump.  See docs/swarm.md.

Every recovery ends in the normal ``SearchOutcome`` end-condition
vocabulary — never a silent partial verdict — with ``retries``,
``failovers``, ``engine``, and ``resumed_from_depth`` reported on the
outcome.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
import warnings
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.tpu import checkpoint as ckpt_mod

__all__ = ["TransientDeviceError", "DispatchTimeout", "EngineFailure",
           "SupervisorExhausted", "RetryPolicy", "FaultRule", "FaultPlan",
           "DispatchBoundary", "SearchSupervisor", "classify_failure",
           "install_retry", "probe_device"]

# In-process watchdog abandonment LEAKS a blocked daemon thread (a
# wedged XLA runtime cannot be interrupted from Python).  Past this many
# still-blocked threads the boundary warns that the process is
# degrading and process isolation (tpu/warden.py) is the right mode.
ABANDONED_WARN_THRESHOLD = int(os.environ.get("DSLABS_ABANDONED_WARN",
                                              "2"))


class TransientDeviceError(RuntimeError):
    """A retryable device/runtime failure (the injectable stand-in for
    an XLA transient status on real hardware)."""


class DispatchTimeout(RuntimeError):
    """A dispatch exceeded its wall-clock deadline (wedged device).
    Never retried in place — the dispatch was abandoned, so the rung's
    device state is unknown; recovery is failover-from-checkpoint."""


class EngineFailure(RuntimeError):
    """A rung of the ladder failed past recovery-in-place.  ``kind`` is
    ``"fatal"`` / ``"retries_exhausted"`` / ``"wedged"`` /
    ``"capacity"`` (a classified CapacityOverflow the capacity ladder
    answered with a spill-enabled retry — docs/capacity.md); ``cause``
    is the underlying exception."""

    def __init__(self, engine: str, kind: str, cause: BaseException):
        super().__init__(f"{engine} engine failed ({kind}): "
                         f"{type(cause).__name__}: {cause}")
        self.engine = engine
        self.kind = kind
        self.cause = cause


class SupervisorExhausted(RuntimeError):
    """Every rung of the failover ladder failed.  ``failures`` holds the
    per-rung :class:`EngineFailure` chain — the full recovery story is
    attributable, never a bare crash."""

    def __init__(self, failures: List[EngineFailure]):
        super().__init__(
            "all failover rungs failed: "
            + "; ".join(str(f) for f in failures))
        self.failures = failures


# Status markers that make a real runtime error retryable: the set a
# production JAX stack treats as preemption/transient (jaxlib surfaces
# them inside XlaRuntimeError messages).
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "ABORTED",
                      "DEADLINE_EXCEEDED", "preempt", "slice restart",
                      "connection reset")
# Exception TYPE NAMES treated as runtime-layer errors (matched by name:
# jaxlib's concrete classes move between versions and must not be a hard
# import dependency).
_RUNTIME_ERROR_NAMES = ("XlaRuntimeError", "JaxRuntimeError")

# Errors the boundary must NEVER absorb: semantic/config failures where
# retry or failover would mask a wrong answer, plus interrupts.
def _passthrough_types() -> tuple:
    from dslabs_tpu.tpu.engine import CapacityOverflow

    return (CapacityOverflow, ckpt_mod.CheckpointMismatch,
            KeyboardInterrupt, SystemExit)


def classify_failure(exc: BaseException) -> str:
    """``"transient"`` (retry in place), ``"wedged"`` (abandon, fail
    over), or ``"fatal"`` (fail over)."""
    if isinstance(exc, DispatchTimeout):
        return "wedged"
    if isinstance(exc, TransientDeviceError):
        return "transient"
    if type(exc).__name__ in _RUNTIME_ERROR_NAMES or isinstance(
            exc, MemoryError):
        msg = str(exc)
        if any(m.lower() in msg.lower() for m in _TRANSIENT_MARKERS):
            return "transient"
    return "fatal"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry + watchdog knobs (docs/resilience.md)."""

    max_retries: int = 3          # per ENGINE rung, across its dispatches
    backoff_base: float = 0.05    # first-retry sleep, seconds
    backoff_factor: float = 2.0
    backoff_max: float = 2.0
    jitter: float = 0.25          # +/- fraction of the backoff, seeded
    deadline_secs: Optional[float] = None   # per-dispatch watchdog; None = off
    # Watchdog deadline for the FIRST dispatch at each (engine, site)
    # tag: that call pays the XLA compile, which dwarfs a steady-state
    # dispatch — a steady-state deadline would misread every cold
    # compile as a wedge.  None = 10 x deadline_secs.
    deadline_first_secs: Optional[float] = None
    seed: int = 0

    def first_deadline(self) -> Optional[float]:
        if self.deadline_secs is None:
            return None
        if self.deadline_first_secs is not None:
            return self.deadline_first_secs
        return 10.0 * self.deadline_secs


@dataclasses.dataclass
class FaultRule:
    """One deterministic fault: dispatches ``at .. at+count-1`` of
    ``engine`` (None = any rung) either raise ``error()`` or hang for
    ``hang_secs`` (interruptibly — the watchdog's abandon releases the
    thread).  ``count=None`` fires forever.  ``site`` (the tag suffix,
    e.g. ``"spill_drain"``) narrows the rule to one dispatch SITE and
    switches the ``at``/``count`` window to that site's own dispatch
    index — how the spill-path fault matrix targets
    evict/refilter/reinject dispatches deterministically."""

    kind: str                      # "raise" | "hang"
    at: int = 0
    count: Optional[int] = 1
    engine: Optional[str] = None
    error: type = TransientDeviceError
    message: str = "injected fault"
    hang_secs: float = 3600.0
    site: Optional[str] = None


class FaultPlan:
    """A deterministic schedule of dispatch-boundary faults.

    Indexing is per-engine: each rung counts its own dispatches from 0,
    and RETRIES ADVANCE THE INDEX (a retry is a new dispatch), so
    ``raise_at(k, count=2)`` means "the dispatch reaching index k fails,
    its first retry fails too, the second retry succeeds"."""

    def __init__(self):
        self.rules: List[FaultRule] = []
        self.fired: int = 0

    def raise_at(self, at: int, error: type = TransientDeviceError,
                 engine: Optional[str] = None, count: Optional[int] = 1,
                 message: str = "injected fault",
                 site: Optional[str] = None) -> "FaultPlan":
        self.rules.append(FaultRule("raise", at=at, count=count,
                                    engine=engine, error=error,
                                    message=message, site=site))
        return self

    def raise_always(self, error: type = TransientDeviceError,
                     engine: Optional[str] = None,
                     message: str = "injected fault") -> "FaultPlan":
        return self.raise_at(0, error=error, engine=engine, count=None,
                             message=message)

    def hang_at(self, at: int, engine: Optional[str] = None,
                secs: float = 3600.0, count: Optional[int] = 1,
                site: Optional[str] = None) -> "FaultPlan":
        self.rules.append(FaultRule("hang", at=at, count=count,
                                    engine=engine, hang_secs=secs,
                                    site=site))
        return self

    def match(self, engine: str, index: int, site: Optional[str] = None,
              site_index: Optional[int] = None) -> Optional[FaultRule]:
        for r in self.rules:
            if r.engine is not None and r.engine != engine:
                continue
            if r.site is not None:
                # Site rules window on the SITE's own dispatch index
                # (e.g. "the second spill_drain of the device rung").
                if r.site != site or site_index is None:
                    continue
                idx = site_index
            else:
                idx = index
            if idx < r.at:
                continue
            if r.count is not None and idx >= r.at + r.count:
                continue
            self.fired += 1
            return r
        return None


class DispatchBoundary:
    """The retry/watchdog/fault-injection wrapper every hot-loop device
    dispatch funnels through (``TensorSearch._dispatch``).

    Install on a search with :meth:`install`; tags are
    ``"<engine>.<site>"`` (e.g. ``"sharded.step"``) and the engine half
    keys both the fault plan and the per-rung dispatch/retry counters.
    """

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 plan: Optional[FaultPlan] = None,
                 observer=None, telemetry=None):
        self.policy = policy or RetryPolicy()
        self.plan = plan
        # Optional telemetry recorder (tpu/telemetry.py): retry and
        # wedge decisions become flight-recorder events, and spans read
        # ``retries`` off this boundary via ``search._dispatch_boundary``.
        self.telemetry = telemetry
        self.retries = 0
        self.timeouts = 0
        self.counts: Dict[str, int] = {}
        self.site_counts: Dict[tuple, int] = {}
        self._engine_retries: Dict[str, int] = {}
        self._rng = random.Random(self.policy.seed)
        # Optional per-dispatch observer, called as
        # ``observer(phase, tag, index, depth)`` with phase ``"start"``
        # before the wrapped call and ``"done"`` after it returns — the
        # warden child's heartbeat emitter rides here (tpu/warden.py).
        # Observer exceptions flow through the normal classification.
        self.observer = observer
        # Watchdog-abandoned daemon threads (the in-process mode's
        # unavoidable leak: a wedged XLA dispatch cannot be interrupted
        # from Python, only abandoned).  Tracked so the degradation is
        # VISIBLE — SearchOutcome.abandoned_threads, bench JSON — and
        # warned about past ABANDONED_WARN_THRESHOLD.
        self.abandoned: List[threading.Thread] = []

    def abandoned_alive(self) -> int:
        """Watchdog-abandoned daemon threads still blocked right now."""
        return sum(1 for t in self.abandoned if t.is_alive())

    def install(self, search, engine: Optional[str] = None) -> None:
        """Route ``search``'s dispatches through this boundary.  The
        optional ``engine`` override renames the tag prefix (the
        supervisor uses the rung name so plans written against the
        ladder vocabulary match)."""
        # Per-site watchdog deadline scales, read LIVE from the search:
        # a fused superstep dispatch legitimately runs a whole level's
        # chunk work, so the sharded engine publishes
        # ``_dispatch_deadline_scales = {"superstep": <trip count>}``
        # and the steady-state deadline stretches accordingly
        # (deadline_secs stays calibrated to single-dispatch
        # granularity for every other site).
        self._scales_src = (
            lambda: getattr(search, "_dispatch_deadline_scales", None))
        # Live BFS depth for the observer's heartbeats: every run loop
        # publishes ``_current_depth`` as levels complete.
        self._depth_src = (
            lambda: int(getattr(search, "_current_depth", 0)))
        # Telemetry spans read the retry counter off this attribute to
        # report retries-per-dispatch without new plumbing.
        search._dispatch_boundary = self
        if engine is None:
            search._dispatch_hook = self.dispatch
        else:
            def hook(tag, fn, *args, _e=engine):
                return self.dispatch(
                    _e + "." + tag.split(".", 1)[-1], fn, *args)
            search._dispatch_hook = hook

    # ------------------------------------------------------------ dispatch

    def _depth(self) -> int:
        src = getattr(self, "_depth_src", None)
        return src() if src is not None else 0

    def dispatch(self, tag: str, fn, *args):
        engine = tag.split(".", 1)[0]
        passthrough = _passthrough_types()
        site = tag.split(".", 1)[-1]
        while True:
            idx = self.counts.get(engine, 0)
            self.counts[engine] = idx + 1
            sidx = self.site_counts.get((engine, site), 0)
            self.site_counts[(engine, site)] = sidx + 1
            rule = (self.plan.match(engine, idx, site, sidx)
                    if self.plan else None)
            try:
                if self.observer is not None:
                    # Observer runs INSIDE the try: a fault it raises
                    # (the warden test matrix injects there) is
                    # classified like any dispatch failure, and a retry
                    # re-announces the attempt.
                    self.observer("start", tag, idx, self._depth())
                if rule is not None and rule.kind == "raise":
                    # Raised BEFORE fn runs: the dispatch args (donated
                    # carries included) are untouched, so a retry of the
                    # same call is always well-defined.
                    raise rule.error(f"{rule.message} "
                                     f"[{engine} dispatch {idx}]")
                if self.policy.deadline_secs is not None:
                    out = self._watchdog_call(tag, fn, args, rule)
                else:
                    out = fn(*args)
                if self.observer is not None:
                    self.observer("done", tag, idx, self._depth())
                return out
            except passthrough:
                raise
            except DispatchTimeout as e:
                # The abandoned dispatch may have consumed its donated
                # buffers; there is nothing sound to retry in place.
                self.timeouts += 1
                if self.telemetry is not None:
                    self.telemetry.event("wedged", engine=engine,
                                         site=site, index=idx)
                raise EngineFailure(engine, "wedged", e)
            except Exception as e:  # noqa: BLE001 — classified below
                if classify_failure(e) != "transient":
                    raise EngineFailure(engine, "fatal", e)
                used = self._engine_retries.get(engine, 0)
                if used >= self.policy.max_retries:
                    raise EngineFailure(engine, "retries_exhausted", e)
                self._engine_retries[engine] = used + 1
                self.retries += 1
                if self.telemetry is not None:
                    self.telemetry.event("retry", engine=engine,
                                         site=site, index=idx,
                                         attempt=used + 1,
                                         error=type(e).__name__)
                time.sleep(self._backoff(used))

    def _backoff(self, attempt: int) -> float:
        p = self.policy
        base = min(p.backoff_base * (p.backoff_factor ** attempt),
                   p.backoff_max)
        # Deterministic jitter (seeded RNG): desynchronises retry storms
        # without making CI runs unreproducible.
        return base * (1.0 + p.jitter * (2.0 * self._rng.random() - 1.0))

    def _deadline_scale(self, tag: str) -> float:
        src = getattr(self, "_scales_src", None)
        if src is None:
            return 1.0
        scales = src()
        if not scales:
            return 1.0
        return float(scales.get(tag.split(".", 1)[-1], 1.0))

    def _watchdog_call(self, tag: str, fn, args, rule):
        """Run one dispatch on a watchdog thread; abandon it at the
        deadline.  The first dispatch at each tag gets the compile-
        inclusive grace deadline (RetryPolicy.first_deadline); sites
        with a published deadline scale (superstep granularity — see
        :meth:`DispatchBoundary.install`) stretch the steady-state
        deadline by that factor.  An injected hang waits interruptibly
        AND checks for abandonment before touching the real dispatch,
        so an abandoned fault thread exits cleanly instead of racing
        device work in the background."""
        release = threading.Event()
        box: List[Tuple[str, object]] = []
        seen = getattr(self, "_seen_tags", None)
        if seen is None:
            seen = self._seen_tags = set()
        scaled = self.policy.deadline_secs * self._deadline_scale(tag)
        deadline = (scaled if tag in seen
                    else max(self.policy.first_deadline(), scaled))
        seen.add(tag)

        def work():
            try:
                if rule is not None and rule.kind == "hang":
                    release.wait(rule.hang_secs)
                    if release.is_set():
                        return          # abandoned: never run the dispatch
                box.append(("ok", fn(*args)))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box.append(("err", e))

        th = threading.Thread(target=work, daemon=True,
                              name=f"dslabs-dispatch-{tag}")
        th.start()
        th.join(deadline)
        if th.is_alive():
            release.set()
            # The leak is unavoidable in-process (Python cannot
            # interrupt a blocked XLA call) but must never be
            # invisible: count the still-blocked threads, warn past
            # the threshold, and let the supervisor surface the live
            # count on SearchOutcome.abandoned_threads.
            self.abandoned = [t for t in self.abandoned if t.is_alive()]
            self.abandoned.append(th)
            n_alive = len(self.abandoned)
            if n_alive >= ABANDONED_WARN_THRESHOLD:
                warnings.warn(
                    f"{n_alive} watchdog-abandoned dispatch threads "
                    "are still blocked in this process (a wedged XLA "
                    "runtime cannot be interrupted from Python); the "
                    "in-process ladder is degrading — use process "
                    "isolation (tpu/warden.py, SearchSupervisor("
                    "process_isolation=True)) for hang-proof recovery",
                    RuntimeWarning, stacklevel=2)
            raise DispatchTimeout(
                f"dispatch {tag!r} exceeded its {deadline}s deadline "
                "(wedged device); abandoned")
        kind, val = box[0]
        if kind == "err":
            raise val
        return val


def install_retry(search, policy: Optional[RetryPolicy] = None,
                  plan: Optional[FaultPlan] = None) -> DispatchBoundary:
    """Wrap a single engine's dispatches with retry/backoff (no ladder):
    the light-touch entry point the search backend uses so lab searches
    survive transient device errors without changing verdict flow."""
    boundary = DispatchBoundary(policy, plan)
    boundary.install(search)
    return boundary


def probe_device(deadline_secs: float = 60.0) -> dict:
    """Watchdog-bounded accelerator liveness probe: a tiny matmul
    through the same dispatch boundary the search loops use.  Returns
    ``{platform, n_devices, secs}``; a wedged runtime surfaces as
    :class:`EngineFailure` (kind ``wedged``) instead of a hang —
    ``bench.py``'s preflight is a thin client of this."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.time()
    boundary = DispatchBoundary(
        RetryPolicy(max_retries=0, deadline_secs=deadline_secs))
    devs = jax.devices()

    def _mm():
        x = jnp.ones((256, 256), jnp.float32)
        return jax.block_until_ready(x @ x)

    y = boundary.dispatch("probe.matmul", _mm)
    if float(np.asarray(y)[0, 0]) != 256.0:
        raise RuntimeError("probe matmul returned a wrong result")
    return {"platform": devs[0].platform, "n_devices": len(devs),
            "secs": round(time.time() - t0, 1)}


# ------------------------------------------------------------- supervisor

class SearchSupervisor:
    """Run a tensor search with retry, watchdog, checkpointing, and the
    engine failover ladder.

    ``ladder`` names the rungs to try in order (default
    ``("sharded", "device", "host")``); each rung is built from the
    shared protocol/limits, has the boundary installed, and — when a
    ``checkpoint_path`` is configured and a fingerprint-matching dump
    exists — resumes from the last checkpoint instead of the root.  A
    rung that fails past recovery (fatal error, exhausted retries,
    wedged dispatch) is abandoned and the next rung takes over; its
    verdict is identical by construction (the host loop is the parity
    oracle the device engines are tested against).  The returned
    ``SearchOutcome`` carries ``retries`` / ``failovers`` / ``engine``
    / ``resumed_from_depth`` so no degradation is ever silent."""

    def __init__(self, protocol,
                 ladder: Tuple[str, ...] = ("sharded", "device", "host"),
                 mesh=None,
                 policy: Optional[RetryPolicy] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 strict: bool = True,
                 max_depth: Optional[int] = None,
                 max_secs: Optional[float] = None,
                 chunk: int = 1 << 10,
                 frontier_cap: int = 1 << 14,
                 visited_cap: int = 1 << 20,
                 ev_budget=None,
                 aot_warmup: bool = False,
                 dispatch_observer=None,
                 process_isolation: bool = False,
                 protocol_factory: Optional[str] = None,
                 factory_kwargs: Optional[dict] = None,
                 protocol_transform: Optional[str] = None,
                 warden_kwargs: Optional[dict] = None,
                 portfolio: bool = False,
                 swarm_kwargs: Optional[dict] = None,
                 spill=False,
                 telemetry=None):
        for rung in ladder:
            if rung not in ("sharded", "device", "host"):
                raise ValueError(f"unknown ladder rung {rung!r}")
        self.protocol = protocol
        self.ladder = tuple(ladder)
        self.mesh = mesh
        self.policy = policy or RetryPolicy()
        self.fault_plan = fault_plan
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.strict = strict
        self.max_depth = max_depth
        self.max_secs = max_secs
        self.chunk = chunk
        self.frontier_cap = frontier_cap
        self.visited_cap = visited_cap
        self.ev_budget = ev_budget
        # AOT warm-up of the sharded rung's programs at build time —
        # compile wall-time lands on SearchOutcome.compile_secs instead
        # of inside the first run's measured window (bench.py).
        self.aot_warmup = aot_warmup
        self.dispatch_observer = dispatch_observer
        # Process isolation (tpu/warden.py): the accelerator-facing
        # search loop runs in a SPAWNED CHILD supervised over a pipe —
        # a wedged runtime is SIGKILLed and the next rung's child
        # resumes from the unified checkpoint, instead of the
        # in-process watchdog's leaked-thread abandonment.  The child
        # rebuilds the protocol from ``protocol_factory``
        # ("module:callable" + ``factory_kwargs``, optionally piped
        # through ``protocol_transform``) because live protocol
        # objects hold closures a process boundary cannot carry.
        self.process_isolation = process_isolation
        self.protocol_factory = protocol_factory
        self.factory_kwargs = factory_kwargs
        self.protocol_transform = protocol_transform
        self.warden_kwargs = warden_kwargs
        # Portfolio mode (ISSUE 5, docs/swarm.md): run the swarm
        # explorer (tpu/swarm.py) as a CONCURRENT lane beside the BFS
        # ladder — BFS proves shallow exhaustiveness while diversified
        # deep walkers hunt deep-narrow violations; the first TERMINAL
        # verdict (violation / exception / goal) wins and the losing
        # lane is cancelled at its next loop boundary.  Exhaust
        # verdicts stay BFS-authoritative (a swarm TIME_EXHAUSTED never
        # outranks a BFS SPACE/DEPTH_EXHAUSTED).
        self.portfolio = portfolio
        self.swarm_kwargs = swarm_kwargs
        # The CAPACITY LADDER (ISSUE 6, tpu/spill.py, docs/capacity.md).
        # ``spill=False`` (default): CapacityOverflow passes through
        # unwrapped — the historical contract, still pinned by tests.
        # ``spill="ladder"``: CapacityOverflow becomes a CLASSIFIED,
        # RECOVERABLE failure — the failing rung is rebuilt with the
        # host-RAM spill tier enabled and resumes from the checkpoint;
        # a second overflow escalates to an 8x larger host tier before
        # the next rung takes over.  ``spill=True`` (or a
        # spill.SpillConfig): every rung runs spill-enabled from the
        # start.
        if spill not in (False, True, "ladder"):
            from dslabs_tpu.tpu import spill as spill_mod

            if not isinstance(spill, spill_mod.SpillConfig):
                raise ValueError(
                    "spill must be False, True, 'ladder', or a "
                    f"spill.SpillConfig — got {spill!r}")
        self.spill = spill
        if portfolio and process_isolation:
            raise ValueError(
                "portfolio=True and process_isolation=True are "
                "mutually exclusive (the swarm lane runs in-process)")
        # Unified telemetry (tpu/telemetry.py): attached to every rung
        # it builds, so dispatch spans, rung/failover events, and the
        # final outcome all land in one flight log.
        self.telemetry = telemetry
        self.boundary: Optional[DispatchBoundary] = None
        self.failures: List[EngineFailure] = []
        # Engines are cached per rung so repeated run() calls (e.g. the
        # bench's warm-up-then-measure pattern) reuse the compiled
        # programs; limits are refreshed from the supervisor per run.
        self._engines: Dict[str, object] = {}

    def _engine_spill(self):
        """The spill argument engines are BUILT with (None = off):
        False/"ladder" build plain rungs (the ladder retries with a
        config on overflow); True/SpillConfig enable from the start."""
        if self.spill in (False, "ladder"):
            return None
        return self.spill

    def _build(self, rung: str, spill=None):
        # Plain rungs keep their historical cache key (external code
        # and tests index self._engines["sharded"]); spill-enabled
        # variants key beside them, per host-tier size.
        key = (rung if spill is None
               else (rung, getattr(spill, "host_cap", True)))
        cached = self._engines.get(key)
        if cached is not None:
            cached.max_depth = self.max_depth
            cached.max_secs = self.max_secs
            return cached
        self._engines[key] = s = self._build_fresh(rung, spill)
        return s

    def _build_fresh(self, rung: str, spill=None):
        from dslabs_tpu.tpu.engine import TensorSearch

        ck = {"checkpoint_path": self.checkpoint_path,
              "checkpoint_every": self.checkpoint_every,
              "spill": spill}
        if rung == "sharded":
            import jax

            from dslabs_tpu.tpu.sharded import (ShardedTensorSearch,
                                                make_mesh)

            mesh = self.mesh
            if mesh is None:
                mesh = self.mesh = make_mesh(len(jax.devices()))
            return ShardedTensorSearch(
                self.protocol, mesh, chunk_per_device=self.chunk,
                frontier_cap=self.frontier_cap,
                visited_cap=self.visited_cap, max_depth=self.max_depth,
                max_secs=self.max_secs, strict=self.strict,
                ev_budget=self.ev_budget,
                aot_warmup=self.aot_warmup, **ck)
        return TensorSearch(
            self.protocol, frontier_cap=self.frontier_cap,
            chunk=self.chunk, max_depth=self.max_depth,
            max_secs=self.max_secs, ev_budget=self.ev_budget,
            visited_cap=self.visited_cap, strict=self.strict,
            use_host_visited=(rung == "host"), **ck)

    def _resumable(self, search) -> bool:
        if not self.checkpoint_path:
            return False
        fp = ckpt_mod.peek_fingerprint(self.checkpoint_path)
        return fp is not None and fp == search._ckpt_fingerprint()

    def run(self, resume: bool = False, initial=None,
            check_initial: bool = True):
        """Run the search to a verdict across the ladder.  ``resume``
        opts in to resuming the FIRST rung from an existing checkpoint;
        failover rungs always resume when a matching dump exists (that
        is the point of the checkpoint).  With ``process_isolation``
        set, the whole ladder runs warden-supervised child processes
        instead (identical verdict semantics; see tpu/warden.py)."""
        if self.process_isolation:
            return self._run_isolated(resume=resume, initial=initial)
        if self.portfolio:
            return self._run_portfolio(resume, initial, check_initial)
        return self._run_ladder(resume, initial, check_initial)

    def _run_ladder(self, resume, initial, check_initial, cancel=None):
        """The in-process failover ladder (the pre-portfolio ``run``
        body).  ``cancel`` (a threading.Event) is the portfolio lane's
        first-verdict-wins cut — installed on every rung so a cancelled
        BFS returns at its next level boundary."""
        from dslabs_tpu.tpu.engine import CapacityOverflow

        self.boundary = DispatchBoundary(self.policy, self.fault_plan,
                                         observer=self.dispatch_observer,
                                         telemetry=self.telemetry)
        self.failures = []
        for i, rung in enumerate(self.ladder):
            search = self._build(rung, self._engine_spill())
            self.boundary.install(search, engine=rung)
            if self.telemetry is not None:
                search._telemetry = self.telemetry
            if cancel is not None:
                search._cancel_event = cancel
            do_resume = (resume or i > 0) and self._resumable(search)
            if self.telemetry is not None:
                self.telemetry.event("rung", engine=rung, index=i,
                                     resume=bool(do_resume))
            out = None
            try:
                out = search.run(check_initial=check_initial,
                                 initial=initial, resume=do_resume)
            except EngineFailure as e:
                self.failures.append(e)
                if self.telemetry is not None:
                    self.telemetry.event("failover", engine=rung,
                                         kind=e.kind,
                                         error=str(e.cause)[:200])
            except CapacityOverflow as e:
                if self.spill != "ladder":
                    # The historical contract: semantic/capacity errors
                    # pass through unwrapped unless the caller opted
                    # into the capacity ladder.
                    raise
                self.failures.append(EngineFailure(rung, "capacity", e))
                out = self._capacity_retry(rung, initial, check_initial,
                                           cancel)
                search = self._last_capacity_search or search
            if out is None:
                continue
            out.engine = rung
            out.retries = self.boundary.retries
            out.failovers = len(self.failures)
            out.resumed_from_depth = getattr(
                search, "_resumed_from_depth", 0)
            out.abandoned_threads = self.boundary.abandoned_alive()
            return out
        raise SupervisorExhausted(self.failures)

    def _capacity_retry(self, rung, initial, check_initial, cancel):
        """The capacity ladder's recovery arm (docs/capacity.md): the
        overflowed rung is rebuilt WITH the host-RAM spill tier and
        resumes from the checkpoint (that is the point of the ladder —
        smaller rungs have less capacity, the tier has host RAM); a
        second overflow escalates to an 8x host tier.  Failures land on
        ``self.failures`` with kind ``"capacity"`` so the recovery
        story stays attributable; returns the outcome or None (fall
        through to the next rung)."""
        import dataclasses as _dc

        from dslabs_tpu.tpu import spill as spill_mod
        from dslabs_tpu.tpu.engine import CapacityOverflow

        self._last_capacity_search = None
        base = (self.spill if isinstance(
            self.spill, spill_mod.SpillConfig) else
            spill_mod.SpillConfig())
        for cfg in (base, _dc.replace(base, host_cap=base.host_cap * 8)):
            search = self._build(rung, cfg)
            self.boundary.install(search, engine=rung)
            if self.telemetry is not None:
                search._telemetry = self.telemetry
                self.telemetry.event("capacity_retry", engine=rung,
                                     host_cap=cfg.host_cap)
            if cancel is not None:
                search._cancel_event = cancel
            self._last_capacity_search = search
            try:
                return search.run(check_initial=check_initial,
                                  initial=initial,
                                  resume=self._resumable(search))
            except CapacityOverflow as e:
                self.failures.append(EngineFailure(rung, "capacity", e))
            except EngineFailure as e:
                self.failures.append(e)
                return None
        return None

    # ------------------------------------------------------ portfolio

    def _build_swarm(self):
        from dslabs_tpu.tpu.swarm import SwarmSearch

        kw = dict(self.swarm_kwargs or {})
        kw.setdefault("mesh", self.mesh)
        kw.setdefault("visited_cap", self.visited_cap)
        kw.setdefault("strict", False)
        kw.setdefault("max_secs", self.max_secs)
        kw.setdefault("ev_budget", self.ev_budget)
        if self.checkpoint_path:
            # Swarm rounds checkpoint beside the BFS dump (their
            # fingerprints differ — neither can resume the other's).
            kw.setdefault("checkpoint_path",
                          self.checkpoint_path + ".swarm")
            kw.setdefault("checkpoint_every", self.checkpoint_every)
        return SwarmSearch(self.protocol, **kw)

    def _run_portfolio(self, resume, initial, check_initial):
        """BFS ladder + swarm fleet as concurrent lanes; first terminal
        verdict wins, the loser is cancelled at its next loop boundary.
        Lane outcomes and errors land on ``self.lanes`` so a portfolio
        verdict is always attributable."""
        import threading

        _TERMINAL = ("INVARIANT_VIOLATED", "EXCEPTION_THROWN",
                     "GOAL_FOUND")
        cancel = threading.Event()
        lanes: Dict[str, object] = {}
        self.lanes = lanes

        def record(name, out):
            lanes[name] = out
            if out.end_condition in _TERMINAL:
                lanes.setdefault("winner", name)
                if self.telemetry is not None:
                    # The live monitor's "current lane" feed: a
                    # portfolio watcher sees which lane won, not just
                    # that SOMETHING returned (tpu/telemetry.py
                    # STATUS.json).
                    self.telemetry.event("lane_winner", lane=name,
                                         end=out.end_condition)
                cancel.set()

        def bfs_lane():
            try:
                out = self._run_ladder(resume, initial, check_initial,
                                       cancel=cancel)
                record("bfs", out)
                # Exhaustive BFS verdicts are authoritative: nothing
                # the swarm could still find would change them, so
                # stop the walkers.  (TIME_EXHAUSTED is not — the
                # swarm keeps its remaining budget.)
                if out.end_condition in ("SPACE_EXHAUSTED",
                                         "DEPTH_EXHAUSTED"):
                    lanes.setdefault("winner", "bfs")
                    cancel.set()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                lanes["bfs_err"] = e

        def swarm_lane():
            try:
                sw = self._build_swarm()
                boundary = DispatchBoundary(self.policy,
                                            self.fault_plan,
                                            telemetry=self.telemetry)
                boundary.install(sw, engine="swarm")
                if self.telemetry is not None:
                    sw._telemetry = self.telemetry
                sw._cancel_event = cancel
                out = sw.run(resume=resume, initial=initial,
                             check_initial=False)
                out.engine = "swarm"
                out.retries = boundary.retries
                record("swarm", out)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                lanes["swarm_err"] = e

        if self.telemetry is not None:
            self.telemetry.event("lane", lanes="bfs+swarm")
        threads = [threading.Thread(target=bfs_lane, daemon=True,
                                    name="dslabs-portfolio-bfs"),
                   threading.Thread(target=swarm_lane, daemon=True,
                                    name="dslabs-portfolio-swarm")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        winner = lanes.get("winner")
        if winner is not None:
            return lanes[winner]
        # No terminal verdict: BFS's exhaust outcome is the richer
        # report; a crashed BFS lane falls back to the swarm's.
        if "bfs" in lanes:
            return lanes["bfs"]
        if "swarm" in lanes:
            return lanes["swarm"]
        raise lanes.get("bfs_err") or lanes.get("swarm_err")

    def _run_isolated(self, resume: bool, initial=None):
        """The process-isolation mode: delegate the ladder to a
        :class:`~dslabs_tpu.tpu.warden.Warden` (one spawned child per
        rung, heartbeat-supervised, SIGKILL on wedge, resume from the
        unified checkpoint).  The warden's failure chain lands on
        ``self.failures`` so both modes report recovery the same way."""
        from dslabs_tpu.tpu.warden import Warden

        if initial is not None:
            raise ValueError(
                "process_isolation cannot ship an in-memory initial "
                "state across the process boundary; encode it in the "
                "protocol_factory instead")
        if not self.protocol_factory:
            raise ValueError(
                "process_isolation=True requires protocol_factory="
                "'module:callable' (+ factory_kwargs) — a live protocol "
                "object cannot cross the spawn boundary")
        warden = Warden(
            factory=self.protocol_factory,
            factory_kwargs=self.factory_kwargs,
            transform=self.protocol_transform,
            ladder=self.ladder, policy=self.policy,
            checkpoint_path=self.checkpoint_path,
            checkpoint_every=self.checkpoint_every,
            strict=self.strict, max_depth=self.max_depth,
            max_secs=self.max_secs, chunk=self.chunk,
            frontier_cap=self.frontier_cap,
            visited_cap=self.visited_cap, ev_budget=self.ev_budget,
            aot_warmup=self.aot_warmup, telemetry=self.telemetry,
            **(self.warden_kwargs or {}))
        try:
            return warden.run(resume=resume)
        finally:
            self.failures = warden.failures
