"""Fault-tolerant search supervisor: retry, watchdog, engine failover.

The north-star deployment is an hours-long accelerator job, and before
this module ANY transient device error, preemption, or wedged TPU killed
a run outright.  The supervisor gives the framework the same spine a
production training/inference stack assumes:

* **One dispatch boundary.**  Every device dispatch in the hot loops —
  the sharded chunk step / level promote / stats sync (sharded.py), the
  single-device wave step / promote / scalar sync (engine.py
  ``_run_device``), and the host loop's expand — funnels through
  ``TensorSearch._dispatch(tag, fn, *args)``.  With no supervisor
  installed that is a zero-cost passthrough; the supervisor installs a
  :class:`DispatchBoundary` there.
* **Failure classification + bounded retry.**  Transient runtime errors
  (XLA RESOURCE_EXHAUSTED / UNAVAILABLE / ABORTED, preemptions,
  :class:`TransientDeviceError` from the fault harness) retry in place
  with exponential backoff + deterministic jitter up to
  ``RetryPolicy.max_retries``.  Fatal errors and exhausted budgets
  raise :class:`EngineFailure`.
* **Wall-clock watchdog.**  With ``RetryPolicy.deadline_secs`` set,
  each dispatch runs on a watchdog thread; a dispatch exceeding its
  deadline (wedged device) is ABANDONED — :class:`DispatchTimeout`,
  classified wedged, no retry — and the supervisor restarts on the
  next rung from the last checkpoint.  ``bench.py``'s wedged-TPU
  preflight is a thin client (:func:`probe_device`).
* **Engine failover ladder.**  :class:`SearchSupervisor` runs the
  search on the first healthy rung of ``sharded -> device -> host``
  (the host loop is the parity oracle — every rung has identical
  verdict semantics), resuming each rung from the shared
  engine-agnostic checkpoint (tpu/checkpoint.py) when one exists.
  Semantic errors (``CapacityOverflow``, ``CheckpointMismatch``)
  propagate unchanged — failover can never mask a wrong-config verdict.
* **Deterministic fault injection.**  A :class:`FaultPlan` installed at
  the same boundary makes every recovery path exercisable in CI on CPU
  ("dispatch k of engine E raises", "dispatch j hangs") — see
  tests/test_supervisor.py and ``make fault-smoke``.
* **Process isolation.**  The in-process watchdog can only ABANDON a
  wedged dispatch (the blocked daemon thread leaks — counted on
  ``SearchOutcome.abandoned_threads`` and warned about past
  ``DSLABS_ABANDONED_WARN``).  ``SearchSupervisor(
  process_isolation=True, protocol_factory="module:callable")`` runs
  the ladder through the dispatch warden instead (tpu/warden.py): each
  rung is a SPAWNED CHILD heartbeating over a pipe, a silent child is
  SIGKILLed and reaped, and the next rung's child resumes from the
  unified checkpoint — nothing leaks, and a hard runtime wedge cannot
  take the supervising process down.

* **Elastic degraded-mesh ladder.**  ``SearchSupervisor(elastic=True)``
  expands the ``"sharded"`` rung into a WIDTH ladder
  ``sharded(D) -> sharded(D/2) -> ... -> sharded(2) -> device -> host``
  (:func:`expand_ladder`): losing one chip — or a wedge/fatal error the
  rung cannot absorb — costs HALF the mesh, not all of it, because the
  engine-agnostic checkpoint re-shards the frontier and re-inserts the
  visited keys per owner on whatever mesh resumes it
  (tpu/checkpoint.py).  Every shrink is a ``mesh_shrunk`` telemetry
  event and the verdict carries ``mesh_width`` / ``mesh_shrinks``.
* **Adaptive in-rung degradation.**  A classified OOM/capacity dispatch
  failure (:func:`classify_oom`: MemoryError, RESOURCE_EXHAUSTED /
  out-of-memory markers) first retries IN PLACE from the checkpoint
  with SHRUNK knobs — chunk size and the superstep chunk budget halve
  per re-level, a bounded ladder of ``max_knob_shrinks``
  (DSLABS_KNOB_SHRINKS) — before burning a rung: a transient memory
  spike costs a re-level, not a mesh.  Re-levels are ``knobs_shrunk``
  telemetry events and ``SearchOutcome.knob_retries``.

* **Portfolio mode.**  ``SearchSupervisor(portfolio=True)`` runs the
  device-sharded swarm explorer (tpu/swarm.py) as a CONCURRENT lane
  beside the BFS ladder — the reference's BFS + RandomDFS portfolio
  (SURVEY §2.4) on the accelerator.  The first terminal verdict
  (violation / exception / goal) wins and the losing lane is cancelled
  at its next loop boundary; exhaustive BFS verdicts stay
  authoritative.  Swarm witnesses arrive minimized and
  replay-verified (``SearchOutcome.witness``); swarm rounds
  checkpoint/resume beside the BFS dump.  See docs/swarm.md.

Every recovery ends in the normal ``SearchOutcome`` end-condition
vocabulary — never a silent partial verdict — with ``retries``,
``failovers``, ``engine``, and ``resumed_from_depth`` reported on the
outcome.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import threading
import time
import warnings
from typing import Dict, List, Optional, Tuple

from dslabs_tpu.tpu import checkpoint as ckpt_mod

__all__ = ["TransientDeviceError", "DispatchTimeout", "EngineFailure",
           "SupervisorExhausted", "RetryPolicy", "FaultRule", "FaultPlan",
           "DispatchBoundary", "SearchSupervisor", "classify_failure",
           "classify_oom", "classify_child_death", "CHILD_RC_FAILED",
           "expand_ladder", "install_retry", "probe_device"]

# In-process watchdog abandonment LEAKS a blocked daemon thread (a
# wedged XLA runtime cannot be interrupted from Python).  Past this many
# still-blocked threads the boundary warns that the process is
# degrading and process isolation (tpu/warden.py) is the right mode.
ABANDONED_WARN_THRESHOLD = int(os.environ.get("DSLABS_ABANDONED_WARN",
                                              "2"))


class TransientDeviceError(RuntimeError):
    """A retryable device/runtime failure (the injectable stand-in for
    an XLA transient status on real hardware)."""


class DispatchTimeout(RuntimeError):
    """A dispatch exceeded its wall-clock deadline (wedged device).
    Never retried in place — the dispatch was abandoned, so the rung's
    device state is unknown; recovery is failover-from-checkpoint."""


class EngineFailure(RuntimeError):
    """A rung of the ladder failed past recovery-in-place.  ``kind`` is
    ``"fatal"`` / ``"retries_exhausted"`` / ``"wedged"`` /
    ``"capacity"`` (a classified CapacityOverflow the capacity ladder
    answered with a spill-enabled retry — docs/capacity.md); ``cause``
    is the underlying exception."""

    def __init__(self, engine: str, kind: str, cause: BaseException):
        super().__init__(f"{engine} engine failed ({kind}): "
                         f"{type(cause).__name__}: {cause}")
        self.engine = engine
        self.kind = kind
        self.cause = cause


class SupervisorExhausted(RuntimeError):
    """Every rung of the failover ladder failed.  ``failures`` holds the
    per-rung :class:`EngineFailure` chain — the full recovery story is
    attributable, never a bare crash."""

    def __init__(self, failures: List[EngineFailure]):
        super().__init__(
            "all failover rungs failed: "
            + "; ".join(str(f) for f in failures))
        self.failures = failures


# Status markers that make a real runtime error retryable: the set a
# production JAX stack treats as preemption/transient (jaxlib surfaces
# them inside XlaRuntimeError messages).
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "ABORTED",
                      "DEADLINE_EXCEEDED", "preempt", "slice restart",
                      "connection reset")
# Exception TYPE NAMES treated as runtime-layer errors (matched by name:
# jaxlib's concrete classes move between versions and must not be a hard
# import dependency).
_RUNTIME_ERROR_NAMES = ("XlaRuntimeError", "JaxRuntimeError")

# Errors the boundary must NEVER absorb: semantic/config failures where
# retry or failover would mask a wrong answer, plus interrupts.
def _passthrough_types() -> tuple:
    from dslabs_tpu.tpu.engine import CapacityOverflow

    return (CapacityOverflow, ckpt_mod.CheckpointMismatch,
            KeyboardInterrupt, SystemExit)


def classify_failure(exc: BaseException) -> str:
    """``"transient"`` (retry in place), ``"wedged"`` (abandon, fail
    over), or ``"fatal"`` (fail over)."""
    if isinstance(exc, DispatchTimeout):
        return "wedged"
    if isinstance(exc, TransientDeviceError):
        return "transient"
    if type(exc).__name__ in _RUNTIME_ERROR_NAMES or isinstance(
            exc, MemoryError):
        msg = str(exc)
        if any(m.lower() in msg.lower() for m in _TRANSIENT_MARKERS):
            return "transient"
    return "fatal"


# Markers of a memory/capacity-shaped failure: what the adaptive
# knob-shrink ladder answers with an in-place re-level (halved chunk +
# superstep budget, resume from checkpoint) before burning a rung.
_OOM_MARKERS = ("resource_exhausted", "out of memory", "hbm oom",
                "allocation failure", "oom-kill")


def classify_oom(exc: Optional[BaseException]) -> bool:
    """True when a failure looks like memory/capacity exhaustion — a
    MemoryError, or a runtime error whose message carries an OOM
    marker.  Such failures are worth an in-place knob-shrink retry
    (smaller chunks need less live HBM) where an arbitrary fatal error
    is not."""
    if exc is None:
        return False
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _OOM_MARKERS)


# Exit code a warden/service child uses after REPORTING a classified
# failure over its pipe — a clean "failed", as opposed to an abrupt
# crash/kill.  Lives here (not tpu/warden.py) because the taxonomy
# below is the SHARED vocabulary: the warden's rung failover, the
# elastic ladder's in-process classify_oom, and the service scheduler's
# retry policy (dslabs_tpu/service/scheduler.py) all agree through it
# on what an "oom" is.
CHILD_RC_FAILED = 3

# Stderr-tail markers for the child-death taxonomy: everything
# classify_oom recognises in an exception MESSAGE, plus the exception
# NAMES a dying child's traceback tail shows instead (classify_oom
# gets the live object and uses isinstance; a reaped child leaves only
# text).
_OOM_STDERR_MARKERS = _OOM_MARKERS + ("memoryerror",)


def classify_child_death(exitcode: Optional[int],
                         killed_by_warden: bool,
                         stderr_markers=()) -> str:
    """The ONE child-death taxonomy (ISSUE 11 satellite: the warden's
    exit-code classifier and :func:`classify_oom` used to disagree —
    an abrupt exit whose stderr carried a MemoryError traceback was a
    "crash" to the warden but OOM-shaped to the elastic ladder, so the
    scheduler's retry policy and the knob-shrink re-level pulled in
    different directions).  Pinned by the table-driven test in
    tests/test_service.py:

    * ``wedge``  — the supervising parent SIGKILLed the child after
      heartbeat silence (a hung dispatch / wedged runtime);
    * ``oom``    — an UNPROMPTED SIGKILL (the kernel OOM killer or an
      external ``kill -9``), OR any other abrupt death whose
      ``stderr_markers`` text carries one of the :func:`classify_oom`
      markers (a MemoryError traceback, RESOURCE_EXHAUSTED, an
      oom-kill notice) — either way the memory/host is suspect and the
      right answer is a knob-shrink re-level, not a plain retry;
    * ``failed`` — the child exited :data:`CHILD_RC_FAILED` after
      reporting a classified in-child failure over its pipe;
    * ``crash``  — anything else: another signal (SIGSEGV, SIGBUS, …)
      or an abrupt nonzero exit with no report and no OOM marker.

    ``stderr_markers`` is any iterable of text (a stderr tail, a
    heartbeat detail string); it refines only the abrupt-death kinds —
    a warden kill stays a wedge and a clean report stays failed even
    when earlier stderr chatter mentioned memory."""
    if killed_by_warden:
        return "wedge"
    if exitcode == CHILD_RC_FAILED:
        return "failed"
    if exitcode is not None and exitcode < 0:
        if -exitcode == signal.SIGKILL:
            return "oom"
    elif exitcode == 0:
        return "crash"     # rc 0 with no result: still an abrupt death
    text = " ".join(str(s) for s in stderr_markers).lower()
    if text and any(m in text for m in _OOM_STDERR_MARKERS):
        return "oom"
    return "crash"


def expand_ladder(ladder, full_width: Optional[int] = None,
                  elastic: bool = False):
    """Expand a rung-name ladder into ``(rung, width)`` specs.  With
    ``elastic`` set, every ``"sharded"`` entry becomes the degraded-
    mesh width ladder ``sharded(D) -> sharded(D/2) -> ... ->
    sharded(2)`` (width ``None`` = the full mesh) so a failing mesh
    degrades by halves instead of cliff-dropping to one device.  The
    engine NAME stays ``"sharded"`` for every width — fault plans,
    retry budgets, and dispatch tags keep one stable vocabulary."""
    specs = []
    for rung in ladder:
        specs.append((rung, None))
        if rung == "sharded" and elastic and (full_width or 0) > 2:
            w = int(full_width)
            while w > 2:
                w = max(2, w // 2)
                specs.append(("sharded", w))
    return specs


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded-retry + watchdog knobs (docs/resilience.md)."""

    max_retries: int = 3          # per ENGINE rung, across its dispatches
    backoff_base: float = 0.05    # first-retry sleep, seconds
    backoff_factor: float = 2.0
    backoff_max: float = 2.0
    jitter: float = 0.25          # +/- fraction of the backoff, seeded
    deadline_secs: Optional[float] = None   # per-dispatch watchdog; None = off
    # Watchdog deadline for the FIRST dispatch at each (engine, site)
    # tag: that call pays the XLA compile, which dwarfs a steady-state
    # dispatch — a steady-state deadline would misread every cold
    # compile as a wedge.  None = 10 x deadline_secs.
    deadline_first_secs: Optional[float] = None
    seed: int = 0

    def first_deadline(self) -> Optional[float]:
        if self.deadline_secs is None:
            return None
        if self.deadline_first_secs is not None:
            return self.deadline_first_secs
        return 10.0 * self.deadline_secs


@dataclasses.dataclass
class FaultRule:
    """One deterministic fault: dispatches ``at .. at+count-1`` of
    ``engine`` (None = any rung) either raise ``error()`` or hang for
    ``hang_secs`` (interruptibly — the watchdog's abandon releases the
    thread).  ``count=None`` fires forever.  ``site`` (the tag suffix,
    e.g. ``"spill_drain"``) narrows the rule to one dispatch SITE and
    switches the ``at``/``count`` window to that site's own dispatch
    index — how the spill-path fault matrix targets
    evict/refilter/reinject dispatches deterministically."""

    kind: str                      # "raise" | "hang"
    at: int = 0
    count: Optional[int] = 1
    engine: Optional[str] = None
    error: type = TransientDeviceError
    message: str = "injected fault"
    hang_secs: float = 3600.0
    site: Optional[str] = None


class FaultPlan:
    """A deterministic schedule of dispatch-boundary faults.

    Indexing is per-engine: each rung counts its own dispatches from 0,
    and RETRIES ADVANCE THE INDEX (a retry is a new dispatch), so
    ``raise_at(k, count=2)`` means "the dispatch reaching index k fails,
    its first retry fails too, the second retry succeeds"."""

    def __init__(self):
        self.rules: List[FaultRule] = []
        self.fired: int = 0
        # Every firing, attributably: (engine, site, kind, index) — the
        # chaos soak (tpu/chaos.py) asserts its fault count and site
        # coverage from this log.
        self.fired_log: List[tuple] = []

    def raise_at(self, at: int, error: type = TransientDeviceError,
                 engine: Optional[str] = None, count: Optional[int] = 1,
                 message: str = "injected fault",
                 site: Optional[str] = None) -> "FaultPlan":
        self.rules.append(FaultRule("raise", at=at, count=count,
                                    engine=engine, error=error,
                                    message=message, site=site))
        return self

    def raise_always(self, error: type = TransientDeviceError,
                     engine: Optional[str] = None,
                     message: str = "injected fault") -> "FaultPlan":
        return self.raise_at(0, error=error, engine=engine, count=None,
                             message=message)

    def hang_at(self, at: int, engine: Optional[str] = None,
                secs: float = 3600.0, count: Optional[int] = 1,
                site: Optional[str] = None) -> "FaultPlan":
        self.rules.append(FaultRule("hang", at=at, count=count,
                                    engine=engine, hang_secs=secs,
                                    site=site))
        return self

    def match(self, engine: str, index: int, site: Optional[str] = None,
              site_index: Optional[int] = None) -> Optional[FaultRule]:
        for r in self.rules:
            if r.engine is not None and r.engine != engine:
                continue
            if r.site is not None:
                # Site rules window on the SITE's own dispatch index
                # (e.g. "the second spill_drain of the device rung").
                if r.site != site or site_index is None:
                    continue
                idx = site_index
            else:
                idx = index
            if idx < r.at:
                continue
            if r.count is not None and idx >= r.at + r.count:
                continue
            self.fired += 1
            self.fired_log.append((engine, site, r.kind, idx))
            return r
        return None


class DispatchBoundary:
    """The retry/watchdog/fault-injection wrapper every hot-loop device
    dispatch funnels through (``TensorSearch._dispatch``).

    Install on a search with :meth:`install`; tags are
    ``"<engine>.<site>"`` (e.g. ``"sharded.step"``) and the engine half
    keys both the fault plan and the per-rung dispatch/retry counters.
    """

    def __init__(self, policy: Optional[RetryPolicy] = None,
                 plan: Optional[FaultPlan] = None,
                 observer=None, telemetry=None):
        self.policy = policy or RetryPolicy()
        self.plan = plan
        # Optional telemetry recorder (tpu/telemetry.py): retry and
        # wedge decisions become flight-recorder events, and spans read
        # ``retries`` off this boundary via ``search._dispatch_boundary``.
        self.telemetry = telemetry
        self.retries = 0
        self.timeouts = 0
        self.counts: Dict[str, int] = {}
        self.site_counts: Dict[tuple, int] = {}
        self._engine_retries: Dict[str, int] = {}
        self._rng = random.Random(self.policy.seed)
        # Optional per-dispatch observer, called as
        # ``observer(phase, tag, index, depth)`` with phase ``"start"``
        # before the wrapped call and ``"done"`` after it returns — the
        # warden child's heartbeat emitter rides here (tpu/warden.py).
        # Observer exceptions flow through the normal classification.
        self.observer = observer
        # Watchdog-abandoned daemon threads (the in-process mode's
        # unavoidable leak: a wedged XLA dispatch cannot be interrupted
        # from Python, only abandoned).  Tracked so the degradation is
        # VISIBLE — SearchOutcome.abandoned_threads, bench JSON — and
        # warned about past ABANDONED_WARN_THRESHOLD.
        self.abandoned: List[threading.Thread] = []

    def abandoned_alive(self) -> int:
        """Watchdog-abandoned daemon threads still blocked right now."""
        return sum(1 for t in self.abandoned if t.is_alive())

    def reset_budget(self, engine: str) -> None:
        """Grant ``engine`` a fresh retry budget.  The supervisor calls
        this at every rung (and knob-shrink re-level) start: the
        elastic ladder reuses the engine NAME across its width rungs,
        but the retry budget is per-RUNG — retries spent on the 8-wide
        mesh must not starve the 4-wide one."""
        self._engine_retries.pop(engine, None)

    def install(self, search, engine: Optional[str] = None) -> None:
        """Route ``search``'s dispatches through this boundary.  The
        optional ``engine`` override renames the tag prefix (the
        supervisor uses the rung name so plans written against the
        ladder vocabulary match)."""
        # Per-site watchdog deadline scales, read LIVE from the search:
        # a fused superstep dispatch legitimately runs a whole level's
        # chunk work, so the sharded engine publishes
        # ``_dispatch_deadline_scales = {"superstep": <trip count>}``
        # and the steady-state deadline stretches accordingly
        # (deadline_secs stays calibrated to single-dispatch
        # granularity for every other site).
        self._scales_src = (
            lambda: getattr(search, "_dispatch_deadline_scales", None))
        # Live BFS depth for the observer's heartbeats: every run loop
        # publishes ``_current_depth`` as levels complete.
        self._depth_src = (
            lambda: int(getattr(search, "_current_depth", 0)))
        # Telemetry spans read the retry counter off this attribute to
        # report retries-per-dispatch without new plumbing.
        search._dispatch_boundary = self
        # A (re)installed search may carry freshly built programs — a
        # degraded-width mesh or a knob-shrunk chunk size compiles new
        # executables — so the first dispatch at each tag earns the
        # compile-inclusive grace deadline again.  Without this reset a
        # knob-shrink re-level's first compile would run under the
        # steady deadline and read as a wedge.
        self._seen_tags = set()
        if engine is None:
            search._dispatch_hook = self.dispatch
        else:
            def hook(tag, fn, *args, _e=engine):
                return self.dispatch(
                    _e + "." + tag.split(".", 1)[-1], fn, *args)
            search._dispatch_hook = hook

    # ------------------------------------------------------------ dispatch

    def _depth(self) -> int:
        src = getattr(self, "_depth_src", None)
        return src() if src is not None else 0

    def dispatch(self, tag: str, fn, *args):
        engine = tag.split(".", 1)[0]
        passthrough = _passthrough_types()
        site = tag.split(".", 1)[-1]
        while True:
            idx = self.counts.get(engine, 0)
            self.counts[engine] = idx + 1
            sidx = self.site_counts.get((engine, site), 0)
            self.site_counts[(engine, site)] = sidx + 1
            rule = (self.plan.match(engine, idx, site, sidx)
                    if self.plan else None)
            if rule is not None and self.telemetry is not None:
                # Injections are first-class flight-log events: a chaos
                # soak's recovery timeline names every fault it threw
                # (tpu/chaos.py plans mark themselves ``chaos``).
                self.telemetry.event(
                    "chaos_inject" if getattr(self.plan, "chaos", False)
                    else "fault_inject",
                    engine=engine, site=site, index=idx,
                    fault=rule.kind)
            try:
                if self.observer is not None:
                    # Observer runs INSIDE the try: a fault it raises
                    # (the warden test matrix injects there) is
                    # classified like any dispatch failure, and a retry
                    # re-announces the attempt.
                    self.observer("start", tag, idx, self._depth())
                if rule is not None and rule.kind == "raise":
                    # Raised BEFORE fn runs: the dispatch args (donated
                    # carries included) are untouched, so a retry of the
                    # same call is always well-defined.
                    raise rule.error(f"{rule.message} "
                                     f"[{engine} dispatch {idx}]")
                if self.policy.deadline_secs is not None:
                    out = self._watchdog_call(tag, fn, args, rule)
                else:
                    out = fn(*args)
                if self.observer is not None:
                    self.observer("done", tag, idx, self._depth())
                return out
            except passthrough:
                raise
            except DispatchTimeout as e:
                # The abandoned dispatch may have consumed its donated
                # buffers; there is nothing sound to retry in place.
                self.timeouts += 1
                if self.telemetry is not None:
                    self.telemetry.event("wedged", engine=engine,
                                         site=site, index=idx)
                raise EngineFailure(engine, "wedged", e)
            except Exception as e:  # noqa: BLE001 — classified below
                if classify_failure(e) != "transient":
                    raise EngineFailure(engine, "fatal", e)
                used = self._engine_retries.get(engine, 0)
                if used >= self.policy.max_retries:
                    raise EngineFailure(engine, "retries_exhausted", e)
                self._engine_retries[engine] = used + 1
                self.retries += 1
                if self.telemetry is not None:
                    self.telemetry.event("retry", engine=engine,
                                         site=site, index=idx,
                                         attempt=used + 1,
                                         error=type(e).__name__)
                time.sleep(self._backoff(used))

    def _backoff(self, attempt: int) -> float:
        p = self.policy
        base = min(p.backoff_base * (p.backoff_factor ** attempt),
                   p.backoff_max)
        # Deterministic jitter (seeded RNG): desynchronises retry storms
        # without making CI runs unreproducible.
        return base * (1.0 + p.jitter * (2.0 * self._rng.random() - 1.0))

    def _deadline_scale(self, tag: str) -> float:
        src = getattr(self, "_scales_src", None)
        if src is None:
            return 1.0
        scales = src()
        if not scales:
            return 1.0
        return float(scales.get(tag.split(".", 1)[-1], 1.0))

    def _watchdog_call(self, tag: str, fn, args, rule):
        """Run one dispatch on a watchdog thread; abandon it at the
        deadline.  The first dispatch at each tag gets the compile-
        inclusive grace deadline (RetryPolicy.first_deadline); sites
        with a published deadline scale (superstep granularity — see
        :meth:`DispatchBoundary.install`) stretch the steady-state
        deadline by that factor.  An injected hang waits interruptibly
        AND checks for abandonment before touching the real dispatch,
        so an abandoned fault thread exits cleanly instead of racing
        device work in the background."""
        release = threading.Event()
        box: List[Tuple[str, object]] = []
        seen = getattr(self, "_seen_tags", None)
        if seen is None:
            seen = self._seen_tags = set()
        scaled = self.policy.deadline_secs * self._deadline_scale(tag)
        deadline = (scaled if tag in seen
                    else max(self.policy.first_deadline(), scaled))
        seen.add(tag)

        def work():
            try:
                if rule is not None and rule.kind == "hang":
                    release.wait(rule.hang_secs)
                    if release.is_set():
                        return          # abandoned: never run the dispatch
                box.append(("ok", fn(*args)))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box.append(("err", e))

        th = threading.Thread(target=work, daemon=True,
                              name=f"dslabs-dispatch-{tag}")
        th.start()
        th.join(deadline)
        if th.is_alive():
            release.set()
            # The leak is unavoidable in-process (Python cannot
            # interrupt a blocked XLA call) but must never be
            # invisible: count the still-blocked threads, warn past
            # the threshold, and let the supervisor surface the live
            # count on SearchOutcome.abandoned_threads.
            self.abandoned = [t for t in self.abandoned if t.is_alive()]
            self.abandoned.append(th)
            n_alive = len(self.abandoned)
            if n_alive >= ABANDONED_WARN_THRESHOLD:
                warnings.warn(
                    f"{n_alive} watchdog-abandoned dispatch threads "
                    "are still blocked in this process (a wedged XLA "
                    "runtime cannot be interrupted from Python); the "
                    "in-process ladder is degrading — use process "
                    "isolation (tpu/warden.py, SearchSupervisor("
                    "process_isolation=True)) for hang-proof recovery",
                    RuntimeWarning, stacklevel=2)
            raise DispatchTimeout(
                f"dispatch {tag!r} exceeded its {deadline}s deadline "
                "(wedged device); abandoned")
        kind, val = box[0]
        if kind == "err":
            raise val
        return val


def install_retry(search, policy: Optional[RetryPolicy] = None,
                  plan: Optional[FaultPlan] = None) -> DispatchBoundary:
    """Wrap a single engine's dispatches with retry/backoff (no ladder):
    the light-touch entry point the search backend uses so lab searches
    survive transient device errors without changing verdict flow."""
    boundary = DispatchBoundary(policy, plan)
    boundary.install(search)
    return boundary


def probe_device(deadline_secs: float = 60.0) -> dict:
    """Watchdog-bounded accelerator liveness probe: a tiny matmul
    through the same dispatch boundary the search loops use.  Returns
    ``{platform, n_devices, secs}``; a wedged runtime surfaces as
    :class:`EngineFailure` (kind ``wedged``) instead of a hang —
    ``bench.py``'s preflight is a thin client of this."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.time()
    boundary = DispatchBoundary(
        RetryPolicy(max_retries=0, deadline_secs=deadline_secs))
    devs = jax.devices()

    def _mm():
        x = jnp.ones((256, 256), jnp.float32)
        return jax.block_until_ready(x @ x)

    y = boundary.dispatch("probe.matmul", _mm)
    if float(np.asarray(y)[0, 0]) != 256.0:
        raise RuntimeError("probe matmul returned a wrong result")
    return {"platform": devs[0].platform, "n_devices": len(devs),
            "secs": round(time.time() - t0, 1)}


# ------------------------------------------------------------- supervisor

class SearchSupervisor:
    """Run a tensor search with retry, watchdog, checkpointing, and the
    engine failover ladder.

    ``ladder`` names the rungs to try in order (default
    ``("sharded", "device", "host")``); each rung is built from the
    shared protocol/limits, has the boundary installed, and — when a
    ``checkpoint_path`` is configured and a fingerprint-matching dump
    exists — resumes from the last checkpoint instead of the root.  A
    rung that fails past recovery (fatal error, exhausted retries,
    wedged dispatch) is abandoned and the next rung takes over; its
    verdict is identical by construction (the host loop is the parity
    oracle the device engines are tested against).  The returned
    ``SearchOutcome`` carries ``retries`` / ``failovers`` / ``engine``
    / ``resumed_from_depth`` so no degradation is ever silent."""

    def __init__(self, protocol,
                 ladder: Tuple[str, ...] = ("sharded", "device", "host"),
                 mesh=None,
                 policy: Optional[RetryPolicy] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: int = 0,
                 strict: bool = True,
                 max_depth: Optional[int] = None,
                 max_secs: Optional[float] = None,
                 chunk: int = 1 << 10,
                 frontier_cap: int = 1 << 14,
                 visited_cap: int = 1 << 20,
                 ev_budget=None,
                 aot_warmup: bool = False,
                 dispatch_observer=None,
                 process_isolation: bool = False,
                 protocol_factory: Optional[str] = None,
                 factory_kwargs: Optional[dict] = None,
                 protocol_transform: Optional[str] = None,
                 warden_kwargs: Optional[dict] = None,
                 portfolio: bool = False,
                 swarm_kwargs: Optional[dict] = None,
                 spill=False,
                 telemetry=None,
                 elastic: Optional[bool] = None,
                 max_knob_shrinks: Optional[int] = None,
                 row_exchange: Optional[bool] = None):
        for rung in ladder:
            if rung not in ("sharded", "device", "host"):
                raise ValueError(f"unknown ladder rung {rung!r}")
        self.protocol = protocol
        self.ladder = tuple(ladder)
        self.mesh = mesh
        self.policy = policy or RetryPolicy()
        self.fault_plan = fault_plan
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.strict = strict
        self.max_depth = max_depth
        self.max_secs = max_secs
        self.chunk = chunk
        self.frontier_cap = frontier_cap
        self.visited_cap = visited_cap
        self.ev_budget = ev_budget
        # Fused in-superstep row exchange (ISSUE 12): None defers to
        # the engine's DSLABS_SHARDED_EXCHANGE default; every ladder
        # rung — degraded widths and knob-shrunk re-levels included —
        # is built with the SAME exchange so a failover never silently
        # changes what the verdict's dispatch path was.
        self.row_exchange = row_exchange
        # AOT warm-up of the sharded rung's programs at build time —
        # compile wall-time lands on SearchOutcome.compile_secs instead
        # of inside the first run's measured window (bench.py).
        self.aot_warmup = aot_warmup
        self.dispatch_observer = dispatch_observer
        # Process isolation (tpu/warden.py): the accelerator-facing
        # search loop runs in a SPAWNED CHILD supervised over a pipe —
        # a wedged runtime is SIGKILLed and the next rung's child
        # resumes from the unified checkpoint, instead of the
        # in-process watchdog's leaked-thread abandonment.  The child
        # rebuilds the protocol from ``protocol_factory``
        # ("module:callable" + ``factory_kwargs``, optionally piped
        # through ``protocol_transform``) because live protocol
        # objects hold closures a process boundary cannot carry.
        self.process_isolation = process_isolation
        self.protocol_factory = protocol_factory
        self.factory_kwargs = factory_kwargs
        self.protocol_transform = protocol_transform
        self.warden_kwargs = warden_kwargs
        # Portfolio mode (ISSUE 5, docs/swarm.md): run the swarm
        # explorer (tpu/swarm.py) as a CONCURRENT lane beside the BFS
        # ladder — BFS proves shallow exhaustiveness while diversified
        # deep walkers hunt deep-narrow violations; the first TERMINAL
        # verdict (violation / exception / goal) wins and the losing
        # lane is cancelled at its next loop boundary.  Exhaust
        # verdicts stay BFS-authoritative (a swarm TIME_EXHAUSTED never
        # outranks a BFS SPACE/DEPTH_EXHAUSTED).
        self.portfolio = portfolio
        self.swarm_kwargs = swarm_kwargs
        # The CAPACITY LADDER (ISSUE 6, tpu/spill.py, docs/capacity.md).
        # ``spill=False`` (default): CapacityOverflow passes through
        # unwrapped — the historical contract, still pinned by tests.
        # ``spill="ladder"``: CapacityOverflow becomes a CLASSIFIED,
        # RECOVERABLE failure — the failing rung is rebuilt with the
        # host-RAM spill tier enabled and resumes from the checkpoint;
        # a second overflow escalates to an 8x larger host tier before
        # the next rung takes over.  ``spill=True`` (or a
        # spill.SpillConfig): every rung runs spill-enabled from the
        # start.
        if spill not in (False, True, "ladder"):
            from dslabs_tpu.tpu import spill as spill_mod

            if not isinstance(spill, spill_mod.SpillConfig):
                raise ValueError(
                    "spill must be False, True, 'ladder', or a "
                    f"spill.SpillConfig — got {spill!r}")
        self.spill = spill
        if portfolio and process_isolation:
            raise ValueError(
                "portfolio=True and process_isolation=True are "
                "mutually exclusive (the swarm lane runs in-process)")
        # Unified telemetry (tpu/telemetry.py): attached to every rung
        # it builds, so dispatch spans, rung/failover events, and the
        # final outcome all land in one flight log.
        self.telemetry = telemetry
        # Elastic degraded-mesh ladder (ISSUE 9, docs/resilience.md):
        # expand the "sharded" rung into sharded(D) -> sharded(D/2) ->
        # ... -> sharded(2) so a fatal/wedged mesh rung costs half the
        # chips, not all of them.  Default off (the pinned historical
        # ladder); DSLABS_ELASTIC=1 flips the default.
        if elastic is None:
            elastic = os.environ.get(
                "DSLABS_ELASTIC", "").strip().lower() in ("1", "on",
                                                          "true", "yes")
        self.elastic = bool(elastic)
        # Adaptive in-rung degradation: how many in-place knob-shrink
        # re-levels (halved chunk + superstep budget, resume from
        # checkpoint) an OOM-classified failure gets before the rung
        # burns.
        if max_knob_shrinks is None:
            max_knob_shrinks = int(
                os.environ.get("DSLABS_KNOB_SHRINKS", "2") or "2")
        self.max_knob_shrinks = int(max_knob_shrinks)
        self.knob_retries = 0
        self.mesh_shrinks = 0
        self._degraded_meshes: Dict[int, object] = {}
        self.boundary: Optional[DispatchBoundary] = None
        self.failures: List[EngineFailure] = []
        # Engines are cached per rung so repeated run() calls (e.g. the
        # bench's warm-up-then-measure pattern) reuse the compiled
        # programs; limits are refreshed from the supervisor per run.
        self._engines: Dict[str, object] = {}

    def _engine_spill(self):
        """The spill argument engines are BUILT with (None = off):
        False/"ladder" build plain rungs (the ladder retries with a
        config on overflow); True/SpillConfig enable from the start."""
        if self.spill in (False, "ladder"):
            return None
        return self.spill

    def _full_width(self) -> int:
        """The undegraded mesh width (device count of the configured
        mesh, or every visible device)."""
        if self.mesh is not None:
            return int(self.mesh.devices.size)
        import jax

        return len(jax.devices())

    def _mesh_for(self, width: Optional[int]):
        """The mesh a sharded rung runs on: the configured/full mesh
        for ``width=None``, else a cached DEGRADED mesh over the first
        ``width`` devices of the full one — the elastic ladder's
        "rebuild a smaller mesh" step."""
        from dslabs_tpu.tpu.sharded import make_mesh

        if width is None:
            if self.mesh is None:
                import jax

                self.mesh = make_mesh(len(jax.devices()))
            return self.mesh
        mesh = self._degraded_meshes.get(width)
        if mesh is None:
            if self.mesh is not None:
                import numpy as np
                from jax.sharding import Mesh

                devs = list(self.mesh.devices.flat)[:width]
                mesh = Mesh(np.array(devs), self.mesh.axis_names)
            else:
                mesh = make_mesh(width)
            self._degraded_meshes[width] = mesh
        return mesh

    def _build(self, rung: str, spill=None, width: Optional[int] = None,
               shrink: int = 0):
        # Plain full-width rungs keep their historical cache key
        # (external code and tests index self._engines["sharded"]);
        # spill-enabled variants key beside them per host-tier size,
        # degraded-width / knob-shrunk variants per (width, shrink).
        if spill is None and width is None and shrink == 0:
            key = rung
        elif width is None and shrink == 0:
            key = (rung, getattr(spill, "host_cap", True))
        else:
            key = (rung, getattr(spill, "host_cap", None), width, shrink)
        cached = self._engines.get(key)
        if cached is not None:
            cached.max_depth = self.max_depth
            cached.max_secs = self.max_secs
            return cached
        self._engines[key] = s = self._build_fresh(rung, spill, width,
                                                   shrink)
        return s

    def _build_fresh(self, rung: str, spill=None,
                     width: Optional[int] = None, shrink: int = 0):
        from dslabs_tpu.tpu.engine import TensorSearch

        ck = {"checkpoint_path": self.checkpoint_path,
              "checkpoint_every": self.checkpoint_every,
              "spill": spill}
        # The knob-shrink ladder: each re-level halves the chunk (the
        # live-HBM-per-chunk-step knob) — and, below, the superstep
        # chunk budget — so an OOM retry runs strictly lighter.
        chunk = max(1, self.chunk >> shrink)
        if rung == "sharded":
            from dslabs_tpu.tpu.sharded import ShardedTensorSearch

            base_budget = int(
                os.environ.get("DSLABS_SUPERSTEP_CHUNKS", "16") or "16")
            return ShardedTensorSearch(
                self.protocol, self._mesh_for(width),
                chunk_per_device=chunk,
                superstep_chunks=(max(1, base_budget >> shrink)
                                  if shrink else None),
                frontier_cap=self.frontier_cap,
                visited_cap=self.visited_cap, max_depth=self.max_depth,
                max_secs=self.max_secs, strict=self.strict,
                ev_budget=self.ev_budget,
                row_exchange=self.row_exchange,
                aot_warmup=self.aot_warmup, **ck)
        return TensorSearch(
            self.protocol, frontier_cap=self.frontier_cap,
            chunk=chunk, max_depth=self.max_depth,
            max_secs=self.max_secs, ev_budget=self.ev_budget,
            visited_cap=self.visited_cap, strict=self.strict,
            use_host_visited=(rung == "host"), **ck)

    def _resumable(self, search) -> bool:
        if not self.checkpoint_path:
            return False
        fp = ckpt_mod.peek_fingerprint(self.checkpoint_path)
        return fp is not None and fp == search._ckpt_fingerprint()

    def run(self, resume: bool = False, initial=None,
            check_initial: bool = True):
        """Run the search to a verdict across the ladder.  ``resume``
        opts in to resuming the FIRST rung from an existing checkpoint;
        failover rungs always resume when a matching dump exists (that
        is the point of the checkpoint).  With ``process_isolation``
        set, the whole ladder runs warden-supervised child processes
        instead (identical verdict semantics; see tpu/warden.py)."""
        if self.process_isolation:
            return self._run_isolated(resume=resume, initial=initial)
        if self.portfolio:
            return self._run_portfolio(resume, initial, check_initial)
        return self._run_ladder(resume, initial, check_initial)

    def _run_ladder(self, resume, initial, check_initial, cancel=None):
        """The in-process failover ladder (the pre-portfolio ``run``
        body).  ``cancel`` (a threading.Event) is the portfolio lane's
        first-verdict-wins cut — installed on every rung so a cancelled
        BFS returns at its next level boundary.  With ``elastic`` the
        rung list is the EXPANDED degraded-mesh ladder
        (:func:`expand_ladder`), and an OOM-classified failure first
        retries the rung in place with shrunk knobs (the adaptive
        knob-shrink ladder) before failing over."""
        from dslabs_tpu.tpu.engine import CapacityOverflow

        self.boundary = DispatchBoundary(self.policy, self.fault_plan,
                                         observer=self.dispatch_observer,
                                         telemetry=self.telemetry)
        self.failures = []
        self.knob_retries = 0
        self.mesh_shrinks = 0
        specs = expand_ladder(
            self.ladder,
            self._full_width() if self.elastic else None, self.elastic)
        prev_width = None
        for i, (rung, width) in enumerate(specs):
            eff_width = None
            if rung == "sharded":
                eff_width = width or self._full_width()
                if prev_width is not None and eff_width < prev_width:
                    # A burned mesh rung degrades by HALVES, resuming
                    # the unified checkpoint re-sharded to the smaller
                    # owner map — the telemetry recovery timeline shows
                    # every step down.
                    self.mesh_shrinks += 1
                    if self.telemetry is not None:
                        self.telemetry.event("mesh_shrunk",
                                             from_width=prev_width,
                                             to_width=eff_width)
                prev_width = eff_width
            shrink = 0
            out = None
            search = None
            while True:
                search = self._build(rung, self._engine_spill(),
                                     width=width, shrink=shrink)
                self.boundary.install(search, engine=rung)
                self.boundary.reset_budget(rung)
                if self.telemetry is not None:
                    search._telemetry = self.telemetry
                if cancel is not None:
                    search._cancel_event = cancel
                do_resume = ((resume or i > 0 or shrink > 0)
                             and self._resumable(search))
                if self.telemetry is not None:
                    self.telemetry.event("rung", engine=rung, index=i,
                                         resume=bool(do_resume),
                                         width=eff_width or 1,
                                         shrink=shrink)
                try:
                    out = search.run(check_initial=check_initial,
                                     initial=initial, resume=do_resume)
                except EngineFailure as e:
                    if (classify_oom(e.cause)
                            and shrink < self.max_knob_shrinks):
                        # Adaptive in-rung degradation: an OOM-shaped
                        # failure retries IN PLACE from the checkpoint
                        # with halved chunk / superstep budget — a
                        # memory spike costs a re-level, not a mesh.
                        shrink += 1
                        self.knob_retries += 1
                        if self.telemetry is not None:
                            self.telemetry.event(
                                "knobs_shrunk", engine=rung,
                                shrink=shrink,
                                chunk=max(1, self.chunk >> shrink),
                                width=eff_width or 1,
                                error=str(e.cause)[:200])
                        continue
                    self.failures.append(e)
                    if self.telemetry is not None:
                        # (field name `failure`, not `kind` — the
                        # recorder's positional is already `kind`.)
                        self.telemetry.event("failover", engine=rung,
                                             failure=e.kind,
                                             width=eff_width or 1,
                                             error=str(e.cause)[:200])
                except CapacityOverflow as e:
                    if self.spill != "ladder":
                        # The historical contract: semantic/capacity
                        # errors pass through unwrapped unless the
                        # caller opted into the capacity ladder.
                        raise
                    self.failures.append(
                        EngineFailure(rung, "capacity", e))
                    out = self._capacity_retry(rung, width, shrink,
                                               initial, check_initial,
                                               cancel)
                    search = self._last_capacity_search or search
                break
            if out is None:
                continue
            out.engine = rung
            out.mesh_width = eff_width if eff_width is not None else 1
            out.mesh_shrinks = self.mesh_shrinks
            out.knob_retries = self.knob_retries
            # Causal-trace identity (ISSUE 13): a supervised verdict
            # carries the recorder's trace even when a failover rung
            # produced it (each rung's engine stamps from the SAME
            # attached recorder; this is the belt-and-braces copy for
            # rungs built without one).
            if (getattr(out, "trace_id", None) is None
                    and self.telemetry is not None):
                out.trace_id = self.telemetry.trace_id
            out.retries = self.boundary.retries
            out.failovers = len(self.failures)
            out.resumed_from_depth = getattr(
                search, "_resumed_from_depth", 0)
            out.abandoned_threads = self.boundary.abandoned_alive()
            return out
        raise SupervisorExhausted(self.failures)

    def _capacity_retry(self, rung, width, shrink, initial,
                        check_initial, cancel):
        """The capacity ladder's recovery arm (docs/capacity.md): the
        overflowed rung is rebuilt WITH the host-RAM spill tier and
        resumes from the checkpoint (that is the point of the ladder —
        smaller rungs have less capacity, the tier has host RAM); a
        second overflow escalates to an 8x host tier.  Failures land on
        ``self.failures`` with kind ``"capacity"`` so the recovery
        story stays attributable; returns the outcome or None (fall
        through to the next rung)."""
        import dataclasses as _dc

        from dslabs_tpu.tpu import spill as spill_mod
        from dslabs_tpu.tpu.engine import CapacityOverflow

        self._last_capacity_search = None
        base = (self.spill if isinstance(
            self.spill, spill_mod.SpillConfig) else
            spill_mod.SpillConfig())
        for cfg in (base, _dc.replace(base, host_cap=base.host_cap * 8)):
            search = self._build(rung, cfg, width=width, shrink=shrink)
            self.boundary.install(search, engine=rung)
            if self.telemetry is not None:
                search._telemetry = self.telemetry
                self.telemetry.event("capacity_retry", engine=rung,
                                     host_cap=cfg.host_cap)
            if cancel is not None:
                search._cancel_event = cancel
            self._last_capacity_search = search
            try:
                return search.run(check_initial=check_initial,
                                  initial=initial,
                                  resume=self._resumable(search))
            except CapacityOverflow as e:
                self.failures.append(EngineFailure(rung, "capacity", e))
            except EngineFailure as e:
                self.failures.append(e)
                return None
        return None

    # ------------------------------------------------------ portfolio

    def _build_swarm(self):
        from dslabs_tpu.tpu.swarm import SwarmSearch

        kw = dict(self.swarm_kwargs or {})
        kw.setdefault("mesh", self.mesh)
        kw.setdefault("visited_cap", self.visited_cap)
        kw.setdefault("strict", False)
        kw.setdefault("max_secs", self.max_secs)
        kw.setdefault("ev_budget", self.ev_budget)
        if self.checkpoint_path:
            # Swarm rounds checkpoint beside the BFS dump (their
            # fingerprints differ — neither can resume the other's).
            kw.setdefault("checkpoint_path",
                          self.checkpoint_path + ".swarm")
            kw.setdefault("checkpoint_every", self.checkpoint_every)
        return SwarmSearch(self.protocol, **kw)

    def _run_portfolio(self, resume, initial, check_initial):
        """BFS ladder + swarm fleet as concurrent lanes; first terminal
        verdict wins, the loser is cancelled at its next loop boundary.
        Lane outcomes and errors land on ``self.lanes`` so a portfolio
        verdict is always attributable."""
        import threading

        _TERMINAL = ("INVARIANT_VIOLATED", "EXCEPTION_THROWN",
                     "GOAL_FOUND")
        cancel = threading.Event()
        lanes: Dict[str, object] = {}
        self.lanes = lanes

        def record(name, out):
            lanes[name] = out
            if out.end_condition in _TERMINAL:
                lanes.setdefault("winner", name)
                if self.telemetry is not None:
                    # The live monitor's "current lane" feed: a
                    # portfolio watcher sees which lane won, not just
                    # that SOMETHING returned (tpu/telemetry.py
                    # STATUS.json).
                    self.telemetry.event("lane_winner", lane=name,
                                         end=out.end_condition)
                cancel.set()

        def bfs_lane():
            try:
                out = self._run_ladder(resume, initial, check_initial,
                                       cancel=cancel)
                record("bfs", out)
                # Exhaustive BFS verdicts are authoritative: nothing
                # the swarm could still find would change them, so
                # stop the walkers.  (TIME_EXHAUSTED is not — the
                # swarm keeps its remaining budget.)
                if out.end_condition in ("SPACE_EXHAUSTED",
                                         "DEPTH_EXHAUSTED"):
                    lanes.setdefault("winner", "bfs")
                    cancel.set()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                lanes["bfs_err"] = e

        def swarm_lane():
            try:
                sw = self._build_swarm()
                boundary = DispatchBoundary(self.policy,
                                            self.fault_plan,
                                            telemetry=self.telemetry)
                boundary.install(sw, engine="swarm")
                if self.telemetry is not None:
                    sw._telemetry = self.telemetry
                sw._cancel_event = cancel
                out = sw.run(resume=resume, initial=initial,
                             check_initial=False)
                out.engine = "swarm"
                out.retries = boundary.retries
                record("swarm", out)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                lanes["swarm_err"] = e

        if self.telemetry is not None:
            self.telemetry.event("lane", lanes="bfs+swarm")
        threads = [threading.Thread(target=bfs_lane, daemon=True,
                                    name="dslabs-portfolio-bfs"),
                   threading.Thread(target=swarm_lane, daemon=True,
                                    name="dslabs-portfolio-swarm")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        winner = lanes.get("winner")
        if winner is not None:
            return lanes[winner]
        # No terminal verdict: BFS's exhaust outcome is the richer
        # report; a crashed BFS lane falls back to the swarm's.
        if "bfs" in lanes:
            return lanes["bfs"]
        if "swarm" in lanes:
            return lanes["swarm"]
        raise lanes.get("bfs_err") or lanes.get("swarm_err")

    def _run_isolated(self, resume: bool, initial=None):
        """The process-isolation mode: delegate the ladder to a
        :class:`~dslabs_tpu.tpu.warden.Warden` (one spawned child per
        rung, heartbeat-supervised, SIGKILL on wedge, resume from the
        unified checkpoint).  The warden's failure chain lands on
        ``self.failures`` so both modes report recovery the same way."""
        from dslabs_tpu.tpu.warden import Warden

        if initial is not None:
            raise ValueError(
                "process_isolation cannot ship an in-memory initial "
                "state across the process boundary; encode it in the "
                "protocol_factory instead")
        if not self.protocol_factory:
            raise ValueError(
                "process_isolation=True requires protocol_factory="
                "'module:callable' (+ factory_kwargs) — a live protocol "
                "object cannot cross the spawn boundary")
        wkw = dict(self.warden_kwargs or {})
        wkw.setdefault("elastic", self.elastic)
        warden = Warden(
            factory=self.protocol_factory,
            factory_kwargs=self.factory_kwargs,
            transform=self.protocol_transform,
            ladder=self.ladder, policy=self.policy,
            checkpoint_path=self.checkpoint_path,
            checkpoint_every=self.checkpoint_every,
            strict=self.strict, max_depth=self.max_depth,
            max_secs=self.max_secs, chunk=self.chunk,
            frontier_cap=self.frontier_cap,
            visited_cap=self.visited_cap, ev_budget=self.ev_budget,
            aot_warmup=self.aot_warmup, telemetry=self.telemetry,
            **wkw)
        try:
            return warden.run(resume=resume)
        finally:
            self.failures = warden.failures
