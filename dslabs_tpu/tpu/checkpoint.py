"""Unified, engine-agnostic search checkpoints (atomic .npz snapshots).

Extracted from the sharded engine's round-4 checkpointing (sharded.py)
and generalised so every rung of the failover ladder — the sharded
driver, the single-device device-resident wave loop, and the host-dedup
parity loop — can dump and resume the SAME file (docs/resilience.md).
That engine-portability is what makes supervisor failover
(sharded -> single-device -> host) resumable: the dump stores the
search's SEMANTIC state, not any engine's carry layout:

  frontier      [n, lanes] int32   live frontier rows (occupied only)
  visited_keys  [K, 4]     uint32  occupied visited-table lines (the
                                   128-bit keys; table layout is
                                   rebuilt on load by re-insertion)
  depth / explored / elapsed / vis_over / dropped   scalars
  fp_map        [M, 9]     int64   optional trace chain (sharded
                                   record_trace mode)
  extra__<name> arrays             optional engine-extension arrays
                                   (``SearchCheckpoint.extra``): state a
                                   non-BFS driver needs beyond the core
                                   layout — the swarm explorer
                                   (tpu/swarm.py) stores walker depths,
                                   event histories, PRNG keys, and the
                                   restart seed pool here, and the
                                   host-RAM spill tier (tpu/spill.py)
                                   its running counters as
                                   ``extra__spill_stats``.  Covered by
                                   the content checksum like every
                                   other entry; loaders that do not
                                   know a key simply ignore it.

Spill-mode dumps (tpu/spill.py, docs/capacity.md) stay TIER-AGNOSTIC
on purpose: ``visited_keys`` stores the exact-deduplicated UNION of
the device table and the host tier and ``frontier`` includes every
host-spooled segment, so a non-spill engine resumes a spill dump (if
its table fits the key set), a spill engine resumes any dump (all keys
load into the tier, the device epoch restarts empty), and the host
tier inherits the CRC32 checksum + ``.prev`` rotation below without
any format change — kill-mid-spill resume is bit-exact.

Every dump carries a **config fingerprint** of the search it belongs
to: the protocol's packed-lane shape (protocol name, node/message/timer
widths, net/timer caps, node count) plus the strict and record_trace
flags.  Engine knobs that do not change state identity (chunk sizes,
frontier/visited capacities, device count, ev budgets) are deliberately
EXCLUDED — a dump written by an 8-device sharded run resumes on a
single-device engine, or under a different chunk size, unchanged.
That width-freedom is load-bearing twice over: the supervisor's
ELASTIC degraded-mesh ladder (ISSUE 9, docs/resilience.md) resumes the
same dump on progressively halved meshes (frontier rows re-split into
contiguous per-device shares, visited keys re-inserted per owner), and
the swarm explorer's own fingerprint family follows the same rule (no
D/K pin — walker state redistributes on load, tpu/swarm.py).  A
fingerprint mismatch is refused LOUDLY (:class:`CheckpointMismatch`
names both fingerprints); a checkpoint is never resumed silently into
a search it does not describe.

Writes are torn-write-proof twice over: the dump is written to a tmp
file and ``os.replace``d into place (a kill mid-write leaves the
previous complete dump), the PREVIOUS dump is rotated to ``<path>.prev``
first, and every dump carries a CRC32 content checksum.  The loader
verifies the checksum and falls back to the rotated ``.prev`` dump WITH
A LOUD WARNING on any truncation/corruption — a machine dying mid-write
(the warden's SIGKILL included, tpu/warden.py) costs at most one
checkpoint interval, never the run.  :class:`AsyncCheckpointWriter` is
the shared skip-if-busy background drain (one in-flight dump, never a
queue).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import threading
import warnings
import zlib
from typing import Optional

import numpy as np

__all__ = ["FORMAT_VERSION", "CheckpointMismatch", "CheckpointCorrupt",
           "SearchCheckpoint", "config_fingerprint", "save", "load",
           "peek_fingerprint", "peek_depth", "AsyncCheckpointWriter",
           "default_compile_cache_dir", "default_flight_log",
           "default_status_path", "run_dir_layout"]


def default_compile_cache_dir(checkpoint_path) -> "Optional[str]":
    """The documented default location of the persistent XLA compile
    cache (tpu/compile_cache.py) for a checkpointed search: a
    ``compile_cache/`` directory beside the dump, so a resumable job
    keeps its compiled programs with its state.  ``None`` when no
    checkpoint is configured (the env knob ``DSLABS_COMPILE_CACHE``
    overrides either way)."""
    if not checkpoint_path:
        return None
    return os.path.join(
        os.path.dirname(os.path.abspath(checkpoint_path)),
        "compile_cache")


def default_flight_log(checkpoint_path) -> "Optional[str]":
    """The run-dir convention for the telemetry flight recorder
    (tpu/telemetry.py): a ``flight.jsonl`` beside the dump, so a
    killed/wedged run leaves its last-N-dispatches trail next to the
    state it would have resumed from.  ``None`` when no checkpoint is
    configured."""
    if not checkpoint_path:
        return None
    return os.path.join(
        os.path.dirname(os.path.abspath(checkpoint_path)),
        "flight.jsonl")


def default_status_path(checkpoint_path) -> "Optional[str]":
    """The live-monitor convention (tpu/telemetry.py): an atomic
    ``STATUS.json`` beside the dump, rewritten at level boundaries so
    ``telemetry watch <run-dir>`` can render the run from another
    process.  ``None`` when no checkpoint is configured."""
    if not checkpoint_path:
        return None
    return os.path.join(
        os.path.dirname(os.path.abspath(checkpoint_path)),
        "STATUS.json")


def default_costs_path(checkpoint_path) -> "Optional[str]":
    """The cost-ledger convention (tpu/tracing.py ``CostMeter``): an
    append-only ``COSTS.jsonl`` beside the dump.  The checking service
    keeps ONE ledger at its root (every job charged into it); a
    standalone checkpointed run that wants metering uses this per-run
    location.  ``None`` when no checkpoint is configured."""
    if not checkpoint_path:
        return None
    return os.path.join(
        os.path.dirname(os.path.abspath(checkpoint_path)),
        "COSTS.jsonl")


def run_dir_layout(checkpoint_path) -> dict:
    """Everything a checkpointed run keeps in its directory — the one
    place the layout is defined (docs/observability.md):

      checkpoint        the atomic .npz dump (+ ``.prev`` rotation)
      compile_cache     persistent XLA compile cache (tpu/compile_cache)
      flight_log        telemetry flight recorder (tpu/telemetry.py)
      status            live-monitor STATUS.json (telemetry watch)
      costs             append-only cost ledger (tpu/tracing.py)
    """
    return {
        "checkpoint": checkpoint_path,
        "prev": (checkpoint_path + ".prev") if checkpoint_path else None,
        "compile_cache": default_compile_cache_dir(checkpoint_path),
        "flight_log": default_flight_log(checkpoint_path),
        "status": default_status_path(checkpoint_path),
        "costs": default_costs_path(checkpoint_path),
    }


FORMAT_VERSION = "dslabs-search-ckpt-v7"


class CheckpointMismatch(RuntimeError):
    """A checkpoint's config fingerprint does not match the live search.

    Raised instead of silently resuming (or silently ignoring) a dump
    from a different protocol/capacity configuration — the message
    names BOTH fingerprints so the divergent knob is attributable."""


class CheckpointCorrupt(RuntimeError):
    """Every candidate dump (main and the rotated ``.prev``) failed its
    checksum/read — there is nothing sound to resume.  Raised loudly
    instead of resuming a torn dump or silently starting from the
    root."""


@dataclasses.dataclass
class SearchCheckpoint:
    """The engine-agnostic snapshot of a BFS at a level boundary."""

    fingerprint: str
    depth: int
    explored: int
    elapsed: float
    frontier: np.ndarray        # [n, lanes] int32, live rows only
    visited_keys: np.ndarray    # [K, 4] uint32, occupied lines only
    vis_over: int = 0
    dropped: int = 0
    fp_map: Optional[np.ndarray] = None   # [M, 9] int64 trace chain
    # Engine-extension arrays (saved as ``extra__<name>`` entries): the
    # swarm explorer's walker state rides here — see module docstring.
    extra: Optional[dict] = None


def config_fingerprint(protocol, strict: bool,
                       record_trace: bool = False,
                       symmetry: int = 0) -> str:
    """The semantic identity a dump must share with the search resuming
    it: packed-lane layout + verdict-affecting flags.  Engine-local
    throughput knobs (chunk, caps, mesh size, ev budget) are excluded
    by design — see the module docstring.  ``symmetry`` (the active
    canonicalize pass's permutation count, 0 = off — ISSUE 15) DOES
    participate: a symmetry-reduced dump's visited keys and unique
    counts describe the quotient space, which an unreduced search must
    refuse loudly rather than resume into.  The bit-packed frontier
    ENCODING deliberately does not (it is a storage codec, converted
    loudly on resume via the dump's ``frontier_encoding`` marker)."""
    base = (FORMAT_VERSION, protocol.name, protocol.n_nodes,
            protocol.node_width, protocol.msg_width,
            protocol.timer_width, protocol.net_cap,
            protocol.timer_cap, bool(strict), bool(record_trace))
    if symmetry:
        base = base + (f"sym{symmetry}",)
    # Fault scenarios (ISSUE 19) change the event grid and the reachable
    # space: a scenario dump must never resume into a fault-free search
    # (or a differently-parameterised scenario) and vice versa.  The
    # signature is derived here, not at call sites, so every producer
    # (engine, sharded, swarm seed loader) gets it for free.
    fl = getattr(protocol, "fault", None)
    if fl is not None:
        base = base + (fl.signature(),)
    return repr(base)


def _content_checksum(host: dict) -> np.uint32:
    """CRC32 over every entry's name, dtype/shape, and raw bytes (sorted
    key order; the ``checksum`` entry itself excluded) — the torn-write
    detector the loader verifies before trusting a dump."""
    crc = 0
    for key in sorted(host):
        if key == "checksum":
            continue
        arr = np.asarray(host[key])
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(repr((arr.dtype.str, arr.shape)).encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return np.uint32(crc & 0xFFFFFFFF)


def save(path: str, ckpt: SearchCheckpoint) -> None:
    """Atomic checksummed dump with one-deep rotation: write to
    ``path + '.tmp'``, rotate any existing dump to ``path + '.prev'``,
    then ``os.replace`` the tmp into place.  A kill at ANY point leaves
    at least one complete, checksum-verifiable dump on disk."""
    host = {
        "config": np.bytes_(ckpt.fingerprint.encode()),
        "depth": np.int64(ckpt.depth),
        "explored": np.int64(ckpt.explored),
        "elapsed": np.float64(ckpt.elapsed),
        "vis_over": np.int64(ckpt.vis_over),
        "dropped": np.int64(ckpt.dropped),
        "frontier": np.asarray(ckpt.frontier, np.int32),
        "visited_keys": np.asarray(ckpt.visited_keys, np.uint32),
    }
    if ckpt.fp_map is not None and len(ckpt.fp_map):
        host["fp_map"] = np.asarray(ckpt.fp_map, np.int64)
    for name, arr in (ckpt.extra or {}).items():
        host[f"extra__{name}"] = np.asarray(arr)
    host["checksum"] = _content_checksum(host)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **host)
    if os.path.exists(path):
        os.replace(path, path + ".prev")
    os.replace(tmp, path)
    _archive_level(path, int(ckpt.depth))


def _archive_level(path: str, depth: int) -> None:
    """Per-level checkpoint archiving (ISSUE 16, service/memo.py): when
    ``DSLABS_MEMO_LEVELS`` names a directory, every completed dump is
    ALSO copied there as ``level_<depth>.npz`` — the incremental
    re-check ladder resumes a spec-edited job from the deepest level
    below its divergence bound.  Best-effort by design: the archive
    must never fail a live dump, and every consumer re-verifies the
    copy's own checksum + config fingerprint before seeding from it."""
    lvl_dir = os.environ.get("DSLABS_MEMO_LEVELS")
    if not lvl_dir:
        return
    try:
        os.makedirs(lvl_dir, exist_ok=True)
        dst = os.path.join(lvl_dir, f"level_{depth}.npz")
        tmp = dst + ".tmp"
        shutil.copyfile(path, tmp)
        os.replace(tmp, dst)
    except OSError:
        pass


def _candidates(path: str):
    """Load order: the main dump, then the rotated previous dump."""
    return (path, path + ".prev")


def peek_fingerprint(path: str) -> Optional[str]:
    """The dump's fingerprint WITHOUT loading the arrays (callers that
    only need a resumability boolean must not pay the full load), or
    None when no readable dump exists.  An unreadable/truncated main
    dump falls through to ``.prev`` — resumability must track what the
    loader would actually resume."""
    if not path:
        return None
    for cand in _candidates(path):
        if not os.path.exists(cand):
            continue
        try:
            with np.load(cand) as z:
                if "config" in z.files:
                    return z["config"].item().decode()
        except Exception:
            continue
    return None


def peek_depth(path: str) -> Optional[int]:
    """The dump's checkpointed depth without loading the state arrays
    (the warden's heartbeat reports it as the durable-resume point), or
    None when no readable dump exists."""
    if not path:
        return None
    for cand in _candidates(path):
        if not os.path.exists(cand):
            continue
        try:
            with np.load(cand) as z:
                if "depth" in z.files:
                    return int(z["depth"])
        except Exception:
            continue
    return None


def _load_verified(path: str) -> dict:
    """Read EVERY entry of a dump and verify the content checksum.
    Raises :class:`CheckpointCorrupt` on truncation, unreadable zip
    content, a missing checksum, or a checksum mismatch."""
    try:
        with np.load(path) as z:
            data = {k: z[k] for k in z.files}
    except Exception as e:
        raise CheckpointCorrupt(
            f"{path}: unreadable/truncated checkpoint "
            f"({type(e).__name__}: {e})") from e
    if "config" not in data:
        raise CheckpointCorrupt(
            f"{path}: not a search checkpoint (no config fingerprint)")
    if "checksum" not in data:
        raise CheckpointCorrupt(
            f"{path}: no content checksum (pre-{FORMAT_VERSION} or "
            "torn dump)")
    want = int(np.uint32(data["checksum"]))
    got = int(_content_checksum(data))
    if want != got:
        raise CheckpointCorrupt(
            f"{path}: content checksum mismatch (stored {want:#010x}, "
            f"computed {got:#010x}) — torn or corrupted dump")
    return data


def load(path: str, fingerprint: str) -> Optional[SearchCheckpoint]:
    """Load and VERIFY a dump.  ``None`` when no file exists; a loud
    :class:`CheckpointMismatch` (naming both fingerprints) when the
    dump belongs to a different configuration.  A corrupt/truncated
    main dump (failed checksum, unreadable zip) falls back to the
    rotated ``.prev`` dump with a LOUD warning — one checkpoint
    interval lost, never the run; when every candidate is corrupt the
    loader raises :class:`CheckpointCorrupt` instead of silently
    restarting from the root."""
    if not path:
        return None
    errors = []
    seen_any = False
    for cand in _candidates(path):
        if not os.path.exists(cand):
            continue
        seen_any = True
        try:
            data = _load_verified(cand)
        except CheckpointCorrupt as e:
            warnings.warn(
                f"checkpoint {cand} failed verification ({e}); "
                "falling back to the rotated previous dump",
                RuntimeWarning, stacklevel=2)
            errors.append(e)
            continue
        found = data["config"].item().decode()
        if found != fingerprint:
            raise CheckpointMismatch(
                f"refusing to resume {cand}: checkpoint fingerprint\n"
                f"  {found}\ndoes not match the live search's\n"
                f"  {fingerprint}\n(dump from a different protocol/"
                "capacity config — delete the file or fix the config)")
        return SearchCheckpoint(
            fingerprint=found,
            depth=int(data["depth"]),
            explored=int(data["explored"]),
            elapsed=float(data["elapsed"]),
            frontier=np.asarray(data["frontier"], np.int32),
            visited_keys=np.asarray(data["visited_keys"], np.uint32),
            vis_over=int(data["vis_over"]) if "vis_over" in data else 0,
            dropped=int(data["dropped"]) if "dropped" in data else 0,
            fp_map=(np.asarray(data["fp_map"], np.int64)
                    if "fp_map" in data else None),
            extra=({k[len("extra__"):]: np.asarray(v)
                    for k, v in data.items()
                    if k.startswith("extra__")} or None))
    if not seen_any:
        return None
    raise CheckpointCorrupt(
        f"no readable checkpoint at {path} (main and .prev both failed "
        "verification): " + "; ".join(str(e) for e in errors))


class AsyncCheckpointWriter:
    """Skip-if-busy background dump drain (one thread, never a queue).

    ``kick(fn)`` runs ``fn`` (host readback + :func:`save`) on a daemon
    thread unless a prior dump is still draining — a checkpoint tick
    that lands mid-drain is SKIPPED, not queued, so dumps can never
    back up behind a slow disk.  ``join()`` blocks until the in-flight
    dump (if any) completes; callers must join before reporting an
    outcome a kill-resume test depends on."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None

    def busy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def kick(self, fn) -> bool:
        if self.busy():
            return False
        th = threading.Thread(target=fn, daemon=True)
        self._thread = th
        th.start()
        return True

    def join(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._thread.join()
