"""Unified, engine-agnostic search checkpoints (atomic .npz snapshots).

Extracted from the sharded engine's round-4 checkpointing (sharded.py)
and generalised so every rung of the failover ladder — the sharded
driver, the single-device device-resident wave loop, and the host-dedup
parity loop — can dump and resume the SAME file (docs/resilience.md).
That engine-portability is what makes supervisor failover
(sharded -> single-device -> host) resumable: the dump stores the
search's SEMANTIC state, not any engine's carry layout:

  frontier      [n, lanes] int32   live frontier rows (occupied only)
  visited_keys  [K, 4]     uint32  occupied visited-table lines (the
                                   128-bit keys; table layout is
                                   rebuilt on load by re-insertion)
  depth / explored / elapsed / vis_over / dropped   scalars
  fp_map        [M, 9]     int64   optional trace chain (sharded
                                   record_trace mode)

Every dump carries a **config fingerprint** of the search it belongs
to: the protocol's packed-lane shape (protocol name, node/message/timer
widths, net/timer caps, node count) plus the strict and record_trace
flags.  Engine knobs that do not change state identity (chunk sizes,
frontier/visited capacities, device count, ev budgets) are deliberately
EXCLUDED — a dump written by an 8-device sharded run resumes on a
single-device engine, or under a different chunk size, unchanged.  A
fingerprint mismatch is refused LOUDLY (:class:`CheckpointMismatch`
names both fingerprints); a checkpoint is never resumed silently into
a search it does not describe.

Writes are atomic (tmp + ``os.replace``): a kill mid-write leaves the
previous complete dump.  :class:`AsyncCheckpointWriter` is the shared
skip-if-busy background drain (one in-flight dump, never a queue).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional

import numpy as np

__all__ = ["FORMAT_VERSION", "CheckpointMismatch", "SearchCheckpoint",
           "config_fingerprint", "save", "load", "peek_fingerprint",
           "AsyncCheckpointWriter", "default_compile_cache_dir"]


def default_compile_cache_dir(checkpoint_path) -> "Optional[str]":
    """The documented default location of the persistent XLA compile
    cache (tpu/compile_cache.py) for a checkpointed search: a
    ``compile_cache/`` directory beside the dump, so a resumable job
    keeps its compiled programs with its state.  ``None`` when no
    checkpoint is configured (the env knob ``DSLABS_COMPILE_CACHE``
    overrides either way)."""
    if not checkpoint_path:
        return None
    return os.path.join(
        os.path.dirname(os.path.abspath(checkpoint_path)),
        "compile_cache")

FORMAT_VERSION = "dslabs-search-ckpt-v6"


class CheckpointMismatch(RuntimeError):
    """A checkpoint's config fingerprint does not match the live search.

    Raised instead of silently resuming (or silently ignoring) a dump
    from a different protocol/capacity configuration — the message
    names BOTH fingerprints so the divergent knob is attributable."""


@dataclasses.dataclass
class SearchCheckpoint:
    """The engine-agnostic snapshot of a BFS at a level boundary."""

    fingerprint: str
    depth: int
    explored: int
    elapsed: float
    frontier: np.ndarray        # [n, lanes] int32, live rows only
    visited_keys: np.ndarray    # [K, 4] uint32, occupied lines only
    vis_over: int = 0
    dropped: int = 0
    fp_map: Optional[np.ndarray] = None   # [M, 9] int64 trace chain


def config_fingerprint(protocol, strict: bool,
                       record_trace: bool = False) -> str:
    """The semantic identity a dump must share with the search resuming
    it: packed-lane layout + verdict-affecting flags.  Engine-local
    throughput knobs (chunk, caps, mesh size, ev budget) are excluded
    by design — see the module docstring."""
    return repr((FORMAT_VERSION, protocol.name, protocol.n_nodes,
                 protocol.node_width, protocol.msg_width,
                 protocol.timer_width, protocol.net_cap,
                 protocol.timer_cap, bool(strict), bool(record_trace)))


def save(path: str, ckpt: SearchCheckpoint) -> None:
    """Atomic dump: write to ``path + '.tmp'``, then ``os.replace``."""
    host = {
        "config": np.bytes_(ckpt.fingerprint.encode()),
        "depth": np.int64(ckpt.depth),
        "explored": np.int64(ckpt.explored),
        "elapsed": np.float64(ckpt.elapsed),
        "vis_over": np.int64(ckpt.vis_over),
        "dropped": np.int64(ckpt.dropped),
        "frontier": np.asarray(ckpt.frontier, np.int32),
        "visited_keys": np.asarray(ckpt.visited_keys, np.uint32),
    }
    if ckpt.fp_map is not None and len(ckpt.fp_map):
        host["fp_map"] = np.asarray(ckpt.fp_map, np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **host)
    os.replace(tmp, path)


def peek_fingerprint(path: str) -> Optional[str]:
    """The dump's fingerprint WITHOUT loading the arrays (callers that
    only need a resumability boolean must not pay the full load), or
    None when the file is missing/unreadable/not a checkpoint."""
    if not path or not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            if "config" not in z.files:
                return None
            return z["config"].item().decode()
    except Exception:
        return None


def load(path: str, fingerprint: str) -> Optional[SearchCheckpoint]:
    """Load and VERIFY a dump: ``None`` when no file exists; a loud
    :class:`CheckpointMismatch` (naming both fingerprints) when the
    dump belongs to a different configuration."""
    if not path or not os.path.exists(path):
        return None
    with np.load(path) as z:
        if "config" not in z.files:
            raise CheckpointMismatch(
                f"{path}: not a search checkpoint (no config "
                "fingerprint)")
        found = z["config"].item().decode()
        if found != fingerprint:
            raise CheckpointMismatch(
                f"refusing to resume {path}: checkpoint fingerprint\n"
                f"  {found}\ndoes not match the live search's\n"
                f"  {fingerprint}\n(dump from a different protocol/"
                "capacity config — delete the file or fix the config)")
        return SearchCheckpoint(
            fingerprint=found,
            depth=int(z["depth"]),
            explored=int(z["explored"]),
            elapsed=float(z["elapsed"]),
            frontier=np.asarray(z["frontier"], np.int32),
            visited_keys=np.asarray(z["visited_keys"], np.uint32),
            vis_over=int(z["vis_over"]) if "vis_over" in z.files else 0,
            dropped=int(z["dropped"]) if "dropped" in z.files else 0,
            fp_map=(np.asarray(z["fp_map"], np.int64)
                    if "fp_map" in z.files else None))


class AsyncCheckpointWriter:
    """Skip-if-busy background dump drain (one thread, never a queue).

    ``kick(fn)`` runs ``fn`` (host readback + :func:`save`) on a daemon
    thread unless a prior dump is still draining — a checkpoint tick
    that lands mid-drain is SKIPPED, not queued, so dumps can never
    back up behind a slow disk.  ``join()`` blocks until the in-flight
    dump (if any) completes; callers must join before reporting an
    outcome a kill-resume test depends on."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None

    def busy(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def kick(self, fn) -> bool:
        if self.busy():
            return False
        th = threading.Thread(target=fn, daemon=True)
        self._thread = th
        th.start()
        return True

    def join(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            self._thread.join()
