"""Batched job lanes: N tenant searches as ONE compiled program.

The dispatch-amortisation half of ROADMAP #2 (ISSUE 14).  The service
stack made many small searches *cheap to host* — persistent compile
cache (PR 3), per-job fault domains (PR 4), the journal queue + DRR
scheduler (PR 11), causal tracing + cost metering (PR 13) — but every
job still paid its own dispatch stream: a small student submission is
dominated by per-level host->device round-trips, not by compute.  This
module applies the engine's own trick one level up: just as states are
vmapped into a frontier, whole JOBS are stacked along a leading lane
axis and advanced by one compiled program.

* **Lane-stacked carry.**  :class:`LaneSearch` stacks L compatible
  jobs' device carries (frontier SoA, per-lane visited tables,
  counters, verdict flags) with a leading ``[L, ...]`` axis and runs
  the EXISTING single-device step body (``TensorSearch._build_dev_step``
  — the exact program the solo engine dispatches) under ``jax.vmap``
  inside a ``lax.while_loop`` *lane superstep*: ONE device dispatch per
  level advances every lane through all of its chunks (event-window
  spill passes included), draining until no lane has work.  All carry
  arithmetic is int32/uint32, so the vmapped body is **bit-identical
  per lane to its solo run** — unique/explored/verdict parity is by
  construction, and pinned by tests/test_lanes.py.
* **Finished lanes are no-ops.**  A lane whose search ended has
  ``cur_n == 0``: the step body's validity masks make every subsequent
  wave a provable no-op on its counters (the same masking that makes
  the solo loop's speculative dispatch safe), so mixed-depth batches
  never corrupt a neighbor.
* **Continuous batching.**  At a level boundary a drained lane is
  refilled from the pending job list by ``lanes.inject`` — a jitted
  one-hot splice of a fresh root carry — with ZERO recompiles: the
  programs are keyed on (lane signature, L) and live in the persistent
  compile cache like every other engine program.
* **Per-lane fault domain inside one process.**  Each lane keeps its
  OWN run dir checkpoint (the engine-agnostic tpu/checkpoint.py dump,
  fingerprint-compatible with a solo resume); a SIGKILL mid-batch
  resumes every lane from its own dump, and a poisoned lane (capacity
  overflow, strict-table pressure) is EVICTED to a solo retry — its
  error never burns a lane-mate (the neighbors' carries are untouched
  by construction).
* **Cost splitting.**  Every shared dispatch's wall clock is divided
  evenly across the lanes resident at that level; a lane's
  ``lane_share`` (shares of a batch sum to 1.0) scales its COSTS.jsonl
  charge (tpu/tracing.py), so per-tenant bills DROP as batching
  improves instead of double-billing the shared program.

Process isolation mirrors tpu/warden.py: :class:`LaneBatchWarden`
spawns ``python -m dslabs_tpu.tpu.lanes`` as one supervised child per
lane batch (heartbeats from the dispatch seam, announced grace,
SIGKILL + classify + resume on silence), streaming per-lane results as
lanes finish so a late crash never loses an early verdict.

Knobs: ``DSLABS_LANES`` (service batch width, 0/1 = off),
``DSLABS_LANE_SWAP`` (continuous batching on/off, default on),
``DSLABS_LANE_RESTARTS`` (batch child respawns before solo eviction).
See docs/service.md "Batched job lanes" and docs/perf.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dslabs_tpu.tpu import visited as visited_mod
from dslabs_tpu.tpu.engine import (SearchOutcome, TensorSearch,
                                   device_get, flatten_state)

__all__ = ["LaneSearch", "LaneJob", "LaneBatchResult", "LaneBatchWarden",
           "job_signature", "lanes_enabled", "lane_swap_enabled"]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def lanes_enabled(default: int = 0) -> int:
    """The service-side batch width: DSLABS_LANES (<= 1 means off)."""
    return max(0, _env_int("DSLABS_LANES", default))


def lane_swap_enabled() -> bool:
    """Continuous batching (refill drained lanes from the pending
    list): DSLABS_LANE_SWAP, default ON whenever lanes are on."""
    return os.environ.get("DSLABS_LANE_SWAP", "1").strip().lower() \
        not in ("0", "off", "false", "no")


def job_signature(job) -> Optional[str]:
    """The scheduler-side lane packing key for a service
    :class:`~dslabs_tpu.service.queue.Job` — two jobs may share a lane
    batch iff this string matches (same factory spec -> same compiled
    twin; same engine knobs -> same program shapes; the engine-side
    twin of :meth:`TensorSearch.lane_signature`).  ``None`` = not
    lane-eligible: chaos-fault jobs, jobs already evicted to solo, and
    jobs whose ladder leads with a non-device rung run alone."""
    if getattr(job, "fault", None) or getattr(job, "solo", False):
        return None
    ladder = tuple(getattr(job, "ladder", ()) or ())
    if ladder and ladder[0] != "device":
        return None
    return json.dumps(
        [job.factory, job.factory_kwargs or {}, job.transform,
         bool(job.strict), int(job.chunk), int(job.frontier_cap),
         int(job.visited_cap)], sort_keys=True)


@dataclasses.dataclass
class LaneJob:
    """One job of a lane batch: identity + per-lane limits + the
    lane's own durable run dir.  The protocol itself is shared — lane
    compatibility (one factory spec, one knob set) is the CALLER's
    contract, enforced upstream by :func:`job_signature`."""

    job_id: str
    max_depth: Optional[int] = None
    max_secs: Optional[float] = None
    checkpoint_path: Optional[str] = None
    checkpoint_every: int = 0
    trace_id: Optional[str] = None
    # Optional batch-1 state pytree to start from (staged searches);
    # host arrays, never crosses a spawn boundary.
    initial: Optional[dict] = None


@dataclasses.dataclass
class LaneBatchResult:
    """What one lane batch produced: per-job verdicts (bit-identical
    to solo runs), per-job eviction errors (poisoned lanes the caller
    retries solo), and the shared-dispatch accounting the cost meter
    splits."""

    outcomes: Dict[str, SearchOutcome]
    errors: Dict[str, str]
    swaps: int = 0
    levels: int = 0
    dispatches: float = 0.0
    device_secs: float = 0.0
    occupancy: float = 0.0          # mean resident lanes per level
    child_restarts: int = 0
    killed_dispatches: int = 0


class _Lane:
    """Host-side state of one resident lane."""

    __slots__ = ("idx", "job", "t0", "depth", "last", "active",
                 "device_secs", "dispatches", "prev_explored")

    def __init__(self, idx: int, job: LaneJob, t0: float,
                 depth: int = 0, last=(0, 1, 0)):
        self.idx = idx
        self.job = job
        self.t0 = t0
        self.depth = depth
        self.last = last            # (explored, unique, vis_over)
        self.active = True
        self.device_secs = 0.0
        self.dispatches = 0.0
        self.prev_explored = last[0]


class LaneSearch(TensorSearch):
    """The lane-stacked engine.  Construction mirrors
    :class:`TensorSearch` (one shared protocol + knob set = the lane
    signature); :meth:`run_lanes` drives a whole batch to per-lane
    verdicts.  Spill and trace recording are solo-only features — a
    job that needs them is not lane-eligible."""

    def __init__(self, protocol, n_lanes: int,
                 frontier_cap: int = 1 << 14,
                 chunk: int = 1 << 10,
                 max_secs: Optional[float] = None,
                 ev_budget=None,
                 visited_cap: int = 1 << 20,
                 strict: bool = True,
                 telemetry=None):
        if n_lanes < 1:
            raise ValueError(f"n_lanes must be >= 1, got {n_lanes}")
        super().__init__(protocol, frontier_cap=frontier_cap,
                         chunk=chunk, max_secs=max_secs,
                         ev_budget=ev_budget, visited_cap=visited_cap,
                         strict=strict, spill=False,
                         telemetry=telemetry)
        self.L = int(n_lanes)
        # The solo loop grows its frontier buffer geometrically; lanes
        # run at the full user cap from the start — counters are
        # cap-independent below the overflow point, and a drop at the
        # user cap is the same CAPACITY_EXHAUSTED verdict the solo
        # run's final growth rung lands (parity-pinned).
        self._cap = -(-frontier_cap // chunk) * chunk
        self._lane_prog_cache: Optional[dict] = None
        self._maybe_sanitize()

    # ------------------------------------------------------------ plumbing

    def _dispatch(self, tag: str, fn, *args):
        # The probe/insert has no Pallas batching rule; pin the
        # bit-identical jnp oracle for anything traced under the lane
        # vmap (trace-time only — solo engines in the same process are
        # untouched, pinned by test).
        with visited_mod.force_jnp():
            return super()._dispatch(tag, fn, *args)

    def _lane_progs(self) -> dict:
        """The jitted lane programs, built once per engine (keyed by
        (lane signature, L) across processes via the persistent XLA
        compile cache — a resident server never recompiles for a new
        batch of the same shape)."""
        if self._lane_prog_cache is not None:
            return self._lane_prog_cache
        import jax
        import jax.numpy as jnp

        cap = self._cap
        C = self.chunk
        L = self.L
        step = self._build_dev_step(cap)
        promote = self._build_dev_promote(cap)
        build = self._build_dev_init(cap)

        def stats_of(carry):
            base = jnp.stack([
                carry["explored"][:, 0], carry["overflow"][:, 0],
                carry["vis_over"][:, 0], carry["f_drop"][:, 0],
                carry["vis_n"][:, 0], carry["nxt_n"][:, 0],
                carry["j"][:, 0]], axis=1)
            return jnp.concatenate(
                [base, carry["flag_cnt"]], axis=1).astype(jnp.int32)

        def superstep(carry, masks):
            # One dispatch = one whole LEVEL for every lane: drain
            # until no lane has an unstepped chunk.  A lane past its
            # own chunk count (or finished: cur_n == 0) no-ops — the
            # step body's validity masks freeze its counters exactly.
            def cond(c):
                return jnp.any(c["j"][:, 0] * C < c["cur_n"][:, 0])

            def body(c):
                c2, _ = jax.vmap(step, in_axes=(0, None))(c, masks)
                return c2

            out = jax.lax.while_loop(cond, body, carry)
            return out, stats_of(out)

        def promote_live(carry, live):
            out = jax.vmap(promote)(carry)
            # Retired lanes (verdict landed / poisoned / swapped out)
            # present an empty frontier from here on.
            out["cur_n"] = jnp.where(live[:, None], out["cur_n"], 0)
            return out

        def init_all(rows0, live):
            carry = jax.vmap(build)(rows0)
            carry["cur_n"] = jnp.where(live[:, None], carry["cur_n"], 0)
            return carry

        def _splice(carry, onehot, fresh):
            def mix(c, f):
                oh = onehot.reshape((L,) + (1,) * (c.ndim - 1))
                return jnp.where(oh, f[None], c)

            return jax.tree.map(mix, carry, fresh)

        def inject(carry, onehot, row0):
            # Continuous-batching swap-in: rebuild ONE lane from a
            # fresh root through the SAME init body solo uses (same
            # table insert, bit-identical lane state).
            return _splice(carry, onehot, build(row0))

        def restore(carry, onehot, lane_carry):
            # Resume splice: a host-rebuilt solo carry (from the
            # lane's own checkpoint) replaces lane ``onehot``.
            return _splice(carry, onehot, lane_carry)

        self._lane_prog_cache = {
            "superstep": jax.jit(superstep, donate_argnums=0),
            "promote": jax.jit(promote_live, donate_argnums=0),
            "init": jax.jit(init_all),
            "inject": jax.jit(inject, donate_argnums=0),
            "restore": jax.jit(restore, donate_argnums=0),
            "builders": {
                "superstep": lambda: jax.jit(superstep,
                                             donate_argnums=0),
                "promote": lambda: jax.jit(promote_live,
                                           donate_argnums=0),
                "init": lambda: jax.jit(init_all),
                "inject": lambda: jax.jit(inject, donate_argnums=0),
            },
        }
        return self._lane_prog_cache

    def dispatch_site_programs(self) -> Dict[str, dict]:
        """Sanitizer registry (ISSUE 10 contract): every lane program
        the batch loop dispatches, with abstract args — so ``analysis
        all`` audits the lane hot path (J1-J5) exactly like the solo
        engines' and a new lane site missing from
        ``telemetry.DISPATCH_SITES`` is a loud J0."""
        import jax
        import jax.numpy as jnp

        with visited_mod.force_jnp():
            progs = self._lane_progs()
            L, cap = self.L, self._cap
            rows_sds = jax.ShapeDtypeStruct((L, 1, self.lanes),
                                            jnp.int32)
            row_sds = jax.ShapeDtypeStruct((1, self.lanes), jnp.int32)
            live_sds = jax.ShapeDtypeStruct((L,), jnp.bool_)
            carry_sds = jax.eval_shape(progs["init"], rows_sds,
                                       live_sds)
            lane_sds = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype),
                carry_sds)
        rt = getattr(self, "_rt_masks", None)
        b = progs["builders"]
        sites = {
            "lanes.init": dict(
                fn=progs["init"], args=(rows_sds, live_sds),
                donate=(), multi=False, builder=b["init"]),
            "lanes.superstep": dict(
                fn=progs["superstep"], args=(carry_sds, rt),
                donate=(0,), multi=False, builder=b["superstep"]),
            "lanes.promote": dict(
                fn=progs["promote"], args=(carry_sds, live_sds),
                donate=(0,), multi=False, builder=b["promote"]),
            "lanes.inject": dict(
                fn=progs["inject"], args=(carry_sds, live_sds, row_sds),
                donate=(0,), multi=False, builder=b["inject"]),
            "lanes.restore": dict(
                fn=progs["restore"], args=(carry_sds, live_sds,
                                           lane_sds),
                donate=(0,), multi=False, builder=None),
            "visited.insert": visited_mod.dispatch_site_program(
                self.visited_cap, self.chunk * self._num_events()),
        }
        return sites

    # ------------------------------------------------------------ helpers

    def _onehot(self, i: int):
        import jax.numpy as jnp

        return jnp.arange(self.L) == i

    def _lane_root(self, job: LaneJob):
        """(state pytree, [1, lanes] root row) for a fresh lane."""
        import jax
        import jax.numpy as jnp

        state = (jax.tree.map(jnp.asarray, job.initial)
                 if job.initial is not None else self.initial_state())
        return state, flatten_state(state)

    def _lane_seed(self, job: LaneJob, resume: bool):
        """How a lane starts: ``("done", outcome)`` (initial-state
        verdict / depth-0 exhaustion / finished checkpoint),
        ``("ckpt", solo_carry, ck)`` (resume splice), or
        ``("fresh", row0)``."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        t0 = time.time()
        if resume and job.checkpoint_path:
            fp = ckpt_mod.peek_fingerprint(job.checkpoint_path)
            if fp is not None and fp == self._ckpt_fingerprint():
                ck = ckpt_mod.load(job.checkpoint_path,
                                   self._ckpt_fingerprint())
                if ck is not None:
                    # Normalize the dump's frontier encoding to raw
                    # (loud cross-encoding conversion, ISSUE 15a) —
                    # _carry_from_ckpt then re-packs to native.
                    self._normalize_ckpt_frontier(ck)
                    if not len(ck.frontier):
                        out = SearchOutcome(
                            "SPACE_EXHAUSTED", ck.explored,
                            len(ck.visited_keys), ck.depth,
                            ck.elapsed, visited_overflow=ck.vis_over)
                        return ("done", out)
                    return ("ckpt", ck)
        state, row0 = self._lane_root(job)
        out = self._check_initial(state, t0)
        if out is not None:
            return ("done", out)
        if job.max_depth is not None and job.max_depth <= 0:
            return ("done", SearchOutcome(
                "DEPTH_EXHAUSTED", 0, 1, 0, time.time() - t0))
        return ("fresh", row0)

    def _lane_terminal(self, rows: np.ndarray, flag_counts,
                       explored: int, vis_n: int, depth: int,
                       elapsed: float, vis_over: int) -> SearchOutcome:
        """Per-lane twin of ``TensorSearch._dev_terminal`` (checkState
        order: exception -> invariant -> goal), over one lane's
        already-fetched flag rows."""
        import jax

        for fi, fname in enumerate(self._flag_names):
            if flag_counts[fi] <= 0:
                continue
            st = jax.tree.map(np.asarray,
                              self.unflatten_rows(rows[fi][None]))
            if fname == "exc":
                return SearchOutcome(
                    "EXCEPTION_THROWN", explored, vis_n, depth, elapsed,
                    violating_state=st, exception_code=int(st["exc"][0]),
                    visited_overflow=vis_over)
            kind, pname = fname.split(":", 1)
            if kind == "inv":
                return SearchOutcome(
                    "INVARIANT_VIOLATED", explored, vis_n, depth,
                    elapsed, violating_state=st, predicate_name=pname,
                    visited_overflow=vis_over)
            return SearchOutcome(
                "GOAL_FOUND", explored, vis_n, depth, elapsed,
                goal_state=st, predicate_name=pname,
                visited_overflow=vis_over)
        raise AssertionError("lane flag counts fired without a name")

    def _lane_ckpt(self, carry, ln: _Lane, nxt_n: int) -> None:
        """One lane's durable dump (post-promote: ``cur`` is the next
        level's frontier) — the engine-agnostic unified format, so a
        poisoned lane's SOLO retry resumes this exact dump."""
        from dslabs_tpu.tpu import checkpoint as ckpt_mod

        i = ln.idx
        if nxt_n:
            frontier = np.asarray(carry["cur"][i][:nxt_n])
        else:
            frontier = np.zeros((0, self.plane), np.int32)
        occ = visited_mod.host_occupied(np.asarray(carry["visited"][i]))
        extra = None
        if self._pk is not None:
            # Lane carries share the solo step body, so cur holds the
            # PACKED encoding (ISSUE 15a) — mark the dump for loud
            # cross-resume conversion like every other writer.
            extra = {"frontier_encoding": np.bytes_(
                self._frontier_encoding().encode())}
        ckpt_mod.save(ln.job.checkpoint_path, ckpt_mod.SearchCheckpoint(
            fingerprint=self._ckpt_fingerprint(), depth=ln.depth,
            explored=ln.last[0], elapsed=time.time() - ln.t0,
            frontier=frontier, visited_keys=occ, vis_over=ln.last[2],
            extra=extra))

    # ----------------------------------------------------------------- run

    def run_lanes(self, jobs: List[LaneJob], resume: bool = False,
                  swap: bool = True,
                  on_lane: Optional[Callable] = None) -> LaneBatchResult:
        """Drive every job to a verdict (or an eviction error).  The
        first L jobs seat immediately; the rest refill drained lanes
        at level boundaries when ``swap`` (continuous batching) is on
        — with it off, overflow jobs run in follow-on seatings of the
        same compiled programs.  ``on_lane(job_id, outcome_or_None,
        error_or_None, lane_secs)`` streams results as lanes retire
        (the batch child forwards them over the pipe, so a late crash
        never loses an early verdict)."""
        import jax.numpy as jnp

        if not jobs:
            return LaneBatchResult({}, {})
        progs = self._lane_progs()
        rt = getattr(self, "_rt_masks", None)
        L = self.L
        nf = len(self._flag_names)
        res = LaneBatchResult({}, {})
        pending = list(jobs)
        t_run = time.time()
        lane_secs: Dict[str, float] = {}

        def _finish(ln: Optional[_Lane], job: LaneJob,
                    out: Optional[SearchOutcome],
                    error: Optional[str]) -> None:
            if out is not None:
                out.engine = "lanes"
                out.lane = ln.idx if ln is not None else None
                out.lane_width = L
                if out.trace_id is None:
                    out.trace_id = job.trace_id
                lane_secs[job.job_id] = (ln.device_secs if ln is not None
                                         else 0.0)
                res.outcomes[job.job_id] = out
                tel = getattr(self, "_telemetry", None)
                if tel is not None:
                    tel.on_outcome(out, engine="lanes")
            else:
                res.errors[job.job_id] = error or "lane error"
                tel = getattr(self, "_telemetry", None)
                if tel is not None:
                    tel.event("lane_evicted", job_id=job.job_id,
                              error=(error or "")[:200])
            if on_lane is not None:
                on_lane(job.job_id, out, error,
                        lane_secs.get(job.job_id, 0.0))

        # ---- seat the initial lanes (one vmapped init dispatch; any
        # resumed lane is then spliced from its own checkpoint).
        lanes: List[Optional[_Lane]] = [None] * L
        splices: List[Tuple[int, object]] = []
        root_rows = np.zeros((L, 1, self.lanes), np.int32)
        live0 = np.zeros((L,), bool)
        i = 0
        while i < L and pending:
            job = pending.pop(0)
            kind, *rest = self._lane_seed(job, resume)
            if kind == "done":
                _finish(None, job, rest[0], None)
                continue
            ln = _Lane(i, job, time.time())
            if kind == "ckpt":
                ck = rest[0]
                ln.t0 = time.time() - ck.elapsed
                ln.depth = ck.depth
                ln.last = (ck.explored, len(ck.visited_keys),
                           ck.vis_over)
                ln.prev_explored = ck.explored
                splices.append((i, ck))
            else:
                root_rows[i] = np.asarray(rest[0])
            lanes[i] = ln
            live0[i] = True
            i += 1
        if not any(live0):
            return res
        carry = self._dispatch("lanes.init", progs["init"],
                               jnp.asarray(root_rows),
                               jnp.asarray(live0))
        res.dispatches += 1.0
        for idx, ck in splices:
            lane_carry = self._carry_from_ckpt(ck, self._cap)
            carry = self._dispatch("lanes.restore", progs["restore"],
                                   carry, self._onehot(idx), lane_carry)
            res.dispatches += 1.0
            lanes[idx].dispatches += 1.0

        def _swap_in(idx: int) -> bool:
            """Refill lane ``idx`` from the pending list; True when a
            job was seated."""
            while pending:
                job = pending.pop(0)
                kind, *rest = self._lane_seed(job, resume)
                if kind == "done":
                    _finish(None, job, rest[0], None)
                    continue
                ln = _Lane(idx, job, time.time())
                nonlocal carry
                if kind == "ckpt":
                    ck = rest[0]
                    ln.t0 = time.time() - ck.elapsed
                    ln.depth = ck.depth
                    ln.last = (ck.explored, len(ck.visited_keys),
                               ck.vis_over)
                    ln.prev_explored = ck.explored
                    lane_carry = self._carry_from_ckpt(ck, self._cap)
                    carry = self._dispatch(
                        "lanes.restore", progs["restore"], carry,
                        self._onehot(idx), lane_carry)
                else:
                    carry = self._dispatch(
                        "lanes.inject", progs["inject"], carry,
                        self._onehot(idx), rest[0])
                res.dispatches += 1.0
                ln.dispatches += 1.0
                lanes[idx] = ln
                res.swaps += 1
                tel = getattr(self, "_telemetry", None)
                if tel is not None:
                    tel.event("lane_swap_in", lane=idx,
                              job_id=job.job_id, depth_neighbors=[
                                  l.depth for l in lanes
                                  if l is not None and l.active])
                return True
            return False

        # ---- the level loop: superstep -> sync -> per-lane verdict
        # extraction -> masked promote -> per-lane checkpoints ->
        # swap-ins.  One superstep + one promote per LEVEL for the
        # whole batch — the amortisation the bench's
        # dispatches-per-job phase measures.
        tel = getattr(self, "_telemetry", None)
        while True:
            active = [ln for ln in lanes if ln is not None and ln.active]
            if not active:
                break
            self._current_depth = max(ln.depth for ln in active) + 1
            t_level = time.time()
            carry, sdev = self._dispatch("lanes.superstep",
                                         progs["superstep"], carry, rt)
            s = self._dispatch("lanes.sync", device_get, sdev)
            wall = time.time() - t_level
            share = wall / len(active)
            res.dispatches += 2.0
            res.device_secs += wall
            res.levels += 1
            res.occupancy += len(active)
            retiring: List[_Lane] = []
            lane_records = []
            for ln in active:
                ln.depth += 1
                ln.device_secs += share
                ln.dispatches += 2.0 / len(active)
                row = s[ln.idx]
                explored, overflow, vis_over, f_drop, vis_n, nxt_n = (
                    int(x) for x in row[:6])
                flag_counts = np.asarray(row[7:7 + nf])
                elapsed = time.time() - ln.t0
                job = ln.job
                p = self.p
                # The level record covers every lane RESIDENT during
                # this level — retiring lanes included (the monitor
                # must show the level that finished them).
                lane_records.append(
                    (ln, explored - ln.prev_explored, vis_n, nxt_n))
                ln.prev_explored = explored
                ln.last = (explored, vis_n, vis_over)
                if overflow:
                    # The solo contract raises CapacityOverflow; in a
                    # batch the lane is POISONED and evicted to a solo
                    # retry — lane-mates never see it.
                    _finish(ln, job, None,
                            f"CapacityOverflow: {p.name}: net_cap="
                            f"{p.net_cap}, timer_cap={p.timer_cap}, or "
                            f"max_live_sends={p.max_live_sends} "
                            f"overflowed at depth {ln.depth} "
                            f"({overflow} drops)")
                    retiring.append(ln)
                    continue
                if self.strict and (vis_over
                                    or vis_n > 3 * self.visited_cap // 4):
                    _finish(ln, job, None,
                            f"CapacityOverflow: {p.name}: visited "
                            f"table pressure at depth {ln.depth} "
                            f"({vis_n}/{self.visited_cap} occupied, "
                            f"{vis_over} unresolved); raise "
                            "visited_cap or retry solo with spill")
                    retiring.append(ln)
                    continue
                if flag_counts.any():
                    rows = self._dispatch("lanes.flags", device_get,
                                          carry["flag_rows"][ln.idx])
                    res.dispatches += 1.0
                    ln.dispatches += 1.0
                    out = self._lane_terminal(
                        rows, flag_counts, explored, vis_n, ln.depth,
                        elapsed, vis_over)
                    _finish(ln, job, out, None)
                    retiring.append(ln)
                    continue
                if f_drop:
                    out = SearchOutcome(
                        "CAPACITY_EXHAUSTED", explored, vis_n,
                        ln.depth, elapsed, visited_overflow=vis_over)
                    _finish(ln, job, out, None)
                    retiring.append(ln)
                    continue
                if nxt_n == 0:
                    out = SearchOutcome(
                        "SPACE_EXHAUSTED", explored, vis_n, ln.depth,
                        elapsed, visited_overflow=vis_over)
                    _finish(ln, job, out, None)
                    retiring.append(ln)
                    continue
                # Pre-NEXT-level limits, the solo loop's ordering: the
                # completed depth is checked before another level runs.
                if (job.max_depth is not None
                        and ln.depth >= job.max_depth):
                    out = SearchOutcome(
                        "DEPTH_EXHAUSTED", explored, vis_n, ln.depth,
                        elapsed, visited_overflow=vis_over)
                    _finish(ln, job, out, None)
                    retiring.append(ln)
                    continue
                if ((job.max_secs is not None and elapsed > job.max_secs)
                        or (self.max_secs is not None
                            and time.time() - t_run > self.max_secs)
                        or self._cancelled()):
                    out = SearchOutcome(
                        "TIME_EXHAUSTED", explored, vis_n, ln.depth,
                        elapsed, visited_overflow=vis_over,
                        cancelled=self._cancelled())
                    _finish(ln, job, out, None)
                    retiring.append(ln)
                    continue
            if tel is not None:
                from dslabs_tpu.tpu import telemetry as tel_mod

                deltas = [d for _, d, _, _ in lane_records] or [0]
                tel.on_level("lanes", {
                    "depth": max((ln.depth for ln in active)),
                    "wall": round(wall, 4),
                    "explored": sum(ln.last[0] for ln in active),
                    "unique": sum(ln.last[1] for ln in active),
                    "next_frontier": sum(n for _, _, _, n
                                         in lane_records),
                    "load_factor": round(
                        max((ln.last[1] for ln in active))
                        / self.visited_cap, 4),
                    "per_device": {
                        "explored": deltas,
                        "frontier": [n for _, _, _, n in lane_records]
                        or [0],
                        "load_factor": [round(v / self.visited_cap, 4)
                                        for _, _, v, _ in lane_records]
                        or [0.0],
                        "drops": [0] * max(len(lane_records), 1)},
                    "skew": {"explored": tel_mod.skew_metrics(deltas)},
                    # The batched-child monitor block (schema-pinned):
                    # per-lane job/depth/explored so `telemetry watch`
                    # renders every resident lane of one process.
                    "lanes": [{
                        "lane": ln.idx, "job_id": ln.job.job_id,
                        "depth": ln.depth, "explored": ln.last[0],
                        "unique": ln.last[1], "frontier": n}
                        for ln, _, _, n in lane_records],
                })
            for ln in retiring:
                ln.active = False
            live = np.array([ln is not None and ln.active
                             for ln in lanes], bool)
            carry = self._dispatch("lanes.promote", progs["promote"],
                                   carry, jnp.asarray(live))
            res.dispatches += 1.0
            still = [ln for ln in lanes if ln is not None and ln.active]
            for ln in still:
                ln.dispatches += 1.0 / len(still)
            # Post-promote: cur is the NEXT level's frontier — the
            # same boundary the solo device loop dumps at.
            for ln in still:
                if (ln.job.checkpoint_path and ln.job.checkpoint_every
                        and ln.depth % ln.job.checkpoint_every == 0):
                    nxt_n = int(s[ln.idx][5])
                    self._lane_ckpt(carry, ln, nxt_n)
            if swap and pending:
                for idx in range(L):
                    if lanes[idx] is None or not lanes[idx].active:
                        if not _swap_in(idx):
                            break
        # Follow-on seatings when continuous batching is off (same
        # compiled programs — the jobs queue behind the batch).
        if pending:
            tail = self.run_lanes(pending, resume=resume, swap=swap,
                                  on_lane=on_lane)
            res.outcomes.update(tail.outcomes)
            res.errors.update(tail.errors)
            res.swaps += tail.swaps
            res.levels += tail.levels
            res.dispatches += tail.dispatches
            res.device_secs += tail.device_secs
            res.occupancy += tail.occupancy * max(tail.levels, 1)
            for jid in tail.outcomes:
                lane_secs[jid] = (tail.outcomes[jid].lane_share or 0.0
                                  ) * max(tail.device_secs, 0.0)
        if res.levels:
            res.occupancy = round(res.occupancy / res.levels, 3)
        # Cost split: each lane's share of the batch's shared device
        # seconds — the shares of a batch sum to 1.0, so the cost
        # meter (tpu/tracing.py) never double-charges a dispatch.
        for jid, out in res.outcomes.items():
            out.lane_share = (
                round(lane_secs.get(jid, 0.0) / res.device_secs, 6)
                if res.device_secs > 0 else 0.0)
        return res


# --------------------------------------------------------- batch warden

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class LaneBatchWarden:
    """Parent half of the lane-batch fault domain (the tpu/warden.py
    pattern, one child per BATCH): spawn ``python -m
    dslabs_tpu.tpu.lanes``, enforce announced heartbeat grace with
    SIGKILL, collect per-lane results AS THEY STREAM (a late crash
    never loses an early verdict), and respawn with ``resume=True`` so
    every unfinished lane continues from its own checkpoint.  After
    ``max_restarts`` deaths the unfinished jobs come back as per-job
    errors — the caller (service/server.py) evicts them to solo
    retries, never burning finished lane-mates."""

    def __init__(self, factory: str, jobs: List[dict],
                 n_lanes: int,
                 factory_kwargs: Optional[dict] = None,
                 transform: Optional[str] = None,
                 strict: bool = True,
                 chunk: int = 1 << 10,
                 frontier_cap: int = 1 << 14,
                 visited_cap: int = 1 << 20,
                 ev_budget=None,
                 max_secs: Optional[float] = None,
                 run_dir: Optional[str] = None,
                 swap: bool = True,
                 env: Optional[dict] = None,
                 extra_sys_path: Optional[List[str]] = None,
                 boot_grace: float = 240.0,
                 first_grace: Optional[float] = None,
                 steady_grace: float = 120.0,
                 idle_grace: float = 300.0,
                 grace_slack: float = 5.0,
                 fault: Optional[dict] = None,
                 max_restarts: Optional[int] = None,
                 force_cpu: bool = False,
                 telemetry=None):
        self.factory = factory
        self.factory_kwargs = factory_kwargs or {}
        self.transform = transform
        self.jobs = list(jobs)
        self.n_lanes = int(n_lanes)
        self.strict = strict
        self.chunk = chunk
        self.frontier_cap = frontier_cap
        self.visited_cap = visited_cap
        self.ev_budget = ev_budget
        self.max_secs = max_secs
        self.run_dir = run_dir
        self.swap = bool(swap)
        self.env = dict(env or {})
        self.extra_sys_path = list(extra_sys_path or [])
        self.boot_grace = boot_grace
        self.first_grace = (boot_grace if first_grace is None
                            else first_grace)
        self.steady_grace = steady_grace
        self.idle_grace = idle_grace
        self.grace_slack = grace_slack
        self.fault = fault
        self.max_restarts = (max_restarts if max_restarts is not None
                             else _env_int("DSLABS_LANE_RESTARTS", 2))
        self.force_cpu = bool(force_cpu)
        self.telemetry = telemetry
        self.deaths: List[dict] = []
        self.killed_dispatches = 0

    def _spec(self, jobs: List[dict], resume: bool,
              spawn_index: int) -> dict:
        return {
            "factory": self.factory,
            "factory_kwargs": self.factory_kwargs,
            "transform": self.transform,
            "jobs": jobs,
            "n_lanes": min(self.n_lanes, max(len(jobs), 1)),
            "strict": self.strict,
            "chunk": self.chunk,
            "frontier_cap": self.frontier_cap,
            "visited_cap": self.visited_cap,
            "ev_budget": (list(self.ev_budget)
                          if isinstance(self.ev_budget, tuple)
                          else self.ev_budget),
            "max_secs": self.max_secs,
            "run_dir": self.run_dir,
            "swap": self.swap,
            "resume": resume,
            "force_cpu": self.force_cpu,
            "grace": {"boot": self.boot_grace,
                      "first": self.first_grace,
                      "steady": self.steady_grace,
                      "idle": self.idle_grace},
            "fault": self.fault,
            "spawn_index": spawn_index,
        }

    def _child_env(self) -> dict:
        env = dict(os.environ)
        paths = [_REPO_ROOT] + self.extra_sys_path
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(paths)
        env["DSLABS_LANE_CHILD"] = "1"
        if self.force_cpu:
            env["JAX_PLATFORMS"] = "cpu"
        env.update(self.env)
        return env

    def run(self, resume: bool = False) -> LaneBatchResult:
        import queue as queue_mod

        from dslabs_tpu.tpu.supervisor import classify_child_death
        from dslabs_tpu.tpu.warden import LineWatch, outcome_from_dict

        res = LaneBatchResult({}, {})
        lane_secs: Dict[str, float] = {}
        remaining = {j["job_id"]: j for j in self.jobs}
        spawn = 0
        while remaining:
            spec = self._spec(list(remaining.values()),
                              resume or spawn > 0, spawn)
            proc = subprocess.Popen(
                [sys.executable, "-m", "dslabs_tpu.tpu.lanes"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
                env=self._child_env())

            def _tee(line):
                sys.stderr.write(line)
                sys.stderr.flush()

            err_watch = LineWatch(proc, proc.stderr, on_line=_tee)
            try:
                proc.stdin.write(json.dumps(spec))
                proc.stdin.close()
            except BrokenPipeError:
                pass
            msgs: "queue_mod.Queue[dict]" = queue_mod.Queue()

            def _read(stdout=proc.stdout):
                for line in stdout:
                    try:
                        msgs.put(json.loads(line))
                    except ValueError:
                        continue
                msgs.put({"t": "eof"})

            threading.Thread(target=_read, daemon=True).start()
            grace = self.boot_grace
            last_hb: Optional[dict] = None
            death: Optional[dict] = None
            finished = False
            while True:
                try:
                    msg = msgs.get(timeout=grace + self.grace_slack)
                except queue_mod.Empty:
                    try:
                        proc.kill()
                    except OSError:
                        pass
                    proc.wait()
                    in_dispatch = (last_hb is not None
                                   and last_hb.get("phase") == "start")
                    if in_dispatch:
                        self.killed_dispatches += 1
                    death = {"kind": "wedge",
                             "detail": (f"lane child silent > "
                                        f"{grace:.1f}s; SIGKILLed"),
                             "exitcode": proc.returncode,
                             "last_hb": last_hb}
                    break
                t = msg.get("t")
                if t == "hb":
                    last_hb = msg
                    grace = float(msg.get("grace", self.steady_grace))
                    if self.telemetry is not None:
                        self.telemetry.event(
                            "heartbeat", rung="lanes",
                            phase=msg.get("phase"), tag=msg.get("tag"),
                            n=msg.get("n"), depth=msg.get("depth"),
                            grace=msg.get("grace"))
                    continue
                if t == "lane_result":
                    jid = msg.get("job_id")
                    out = outcome_from_dict(msg["outcome"])
                    res.outcomes[jid] = out
                    lane_secs[jid] = float(msg.get("lane_secs", 0.0)
                                           or 0.0)
                    remaining.pop(jid, None)
                    continue
                if t == "lane_error":
                    jid = msg.get("job_id")
                    res.errors[jid] = msg.get("error", "lane error")
                    remaining.pop(jid, None)
                    continue
                if t == "result":
                    proc.wait()
                    res.swaps += int(msg.get("swaps", 0) or 0)
                    res.levels += int(msg.get("levels", 0) or 0)
                    res.dispatches += float(msg.get("dispatches", 0.0)
                                            or 0.0)
                    res.device_secs += float(msg.get("device_secs",
                                                     0.0) or 0.0)
                    res.occupancy = float(msg.get("occupancy", 0.0)
                                          or 0.0) or res.occupancy
                    finished = True
                    break
                if t == "err":
                    try:
                        rc = proc.wait(timeout=30.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        rc = proc.wait()
                    death = {"kind": classify_child_death(
                                 rc, False, err_watch.tail),
                             "detail": msg.get("error", "lane child "
                                               "failure"),
                             "exitcode": rc, "last_hb": last_hb}
                    break
                if t == "eof":
                    rc = proc.wait()
                    kind = classify_child_death(rc, False,
                                                err_watch.tail)
                    death = {"kind": kind, "exitcode": rc,
                             "last_hb": last_hb,
                             "detail": (f"lane child exited rc={rc} "
                                        f"without a result "
                                        f"(classified {kind})")}
                    break
            if finished:
                break
            self.deaths.append(death)
            res.child_restarts += 1
            if self.telemetry is not None:
                self.telemetry.event(
                    "lane_child_death", kind=death["kind"],
                    exitcode=death.get("exitcode"),
                    detail=death["detail"][:200])
            # A reported deterministic in-child failure ("failed")
            # buys nothing on retry; deaths past the restart budget
            # stop the batch either way.
            if death["kind"] == "failed" or spawn >= self.max_restarts:
                for jid in list(remaining):
                    res.errors[jid] = (f"batch:{death['kind']}: "
                                       f"{death['detail'][:160]}")
                    remaining.pop(jid, None)
                break
            spawn += 1
        # Normalise the cost split over the WHOLE batch (restart
        # children included): shares sum to 1.0 of the accumulated
        # shared device seconds.
        for jid, out in res.outcomes.items():
            out.lane_share = (
                round(lane_secs.get(jid, 0.0) / res.device_secs, 6)
                if res.device_secs > 0 else 0.0)
            out.child_restarts = res.child_restarts
            out.killed_dispatches = self.killed_dispatches
        res.killed_dispatches = self.killed_dispatches
        return res


# ------------------------------------------------------------ child half

def _send(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _resolve(ref: str):
    import importlib

    mod, _, name = ref.partition(":")
    obj = importlib.import_module(mod)
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _child_main() -> int:
    from dslabs_tpu.tpu.warden import outcome_to_dict

    spec = json.load(sys.stdin)
    g = spec.get("grace") or {}
    boot_g = float(g.get("boot", 240.0))
    first_g = float(g.get("first", boot_g))
    steady_g = float(g.get("steady", 120.0))
    idle_g = float(g.get("idle", 300.0))
    _send({"t": "hb", "phase": "boot", "stage": "spawned",
           "grace": boot_g})
    if spec.get("force_cpu"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    proto = _resolve(spec["factory"])(**(spec.get("factory_kwargs")
                                         or {}))
    if spec.get("transform"):
        proto = _resolve(spec["transform"])(proto)
    _send({"t": "hb", "phase": "boot", "stage": "protocol",
           "grace": boot_g})
    ev = spec.get("ev_budget")
    if isinstance(ev, list):
        ev = tuple(ev)
    fault = spec.get("fault")
    if fault is not None:
        if fault.get("spawns") is not None:
            if int(spec.get("spawn_index", 0)) not in fault["spawns"]:
                fault = None
        elif int(spec.get("spawn_index", 0)) > 0:
            fault = None

    # The batch run dir: ONE flight log + STATUS.json for the whole
    # batch (per-lane progress rides the level records' `lanes` block);
    # each lane keeps its own checkpoint in its own job dir.
    child_tel = None
    run_dir = spec.get("run_dir")
    if run_dir:
        try:
            from dslabs_tpu.tpu.telemetry import Telemetry

            os.makedirs(run_dir, exist_ok=True)
            child_tel = Telemetry.for_checkpoint(
                os.path.join(run_dir, "ckpt.npz"),
                engine_hint="lane-batch")
        except Exception:  # noqa: BLE001 — observability is optional
            child_tel = None
    jobs = [LaneJob(job_id=j["job_id"], max_depth=j.get("max_depth"),
                    max_secs=j.get("max_secs"),
                    checkpoint_path=j.get("checkpoint_path"),
                    checkpoint_every=int(j.get("checkpoint_every", 0)
                                         or 0),
                    trace_id=j.get("trace_id"))
            for j in spec.get("jobs", [])]
    if child_tel is not None:
        # Shared-span trace attribution (ISSUE 14): the batch flight
        # log names every resident job + trace id up front, so the
        # trace assembler can attribute each shared dispatch span to
        # every lane's causal tree from disk alone.
        child_tel.event("lane_batch", jobs=[
            {"job_id": j.job_id, "trace_id": j.trace_id}
            for j in jobs], n_lanes=spec.get("n_lanes"))
    search = LaneSearch(
        proto, n_lanes=int(spec.get("n_lanes", 1) or 1),
        frontier_cap=int(spec.get("frontier_cap", 1 << 14)),
        chunk=int(spec.get("chunk", 1 << 10)),
        max_secs=spec.get("max_secs"),
        ev_budget=ev,
        visited_cap=int(spec.get("visited_cap", 1 << 20)),
        strict=bool(spec.get("strict", True)),
        telemetry=child_tel)

    seen_tags = set()
    n_seen = {"n": 0}

    def hook(tag, fn, *args):
        idx = n_seen["n"]
        n_seen["n"] += 1
        first = tag not in seen_tags
        seen_tags.add(tag)
        depth = getattr(search, "_current_depth", 0)
        grace = first_g if first else steady_g
        _send({"t": "hb", "phase": "start", "tag": tag, "n": idx,
               "depth": depth, "grace": grace})
        if fault is not None:
            kind = fault.get("kind")
            at = int(fault.get("at", 0))
            due = (idx >= at if kind in ("die", "exit", "hang")
                   else idx == at)
            if due:
                if kind == "die":
                    os.kill(os.getpid(), signal.SIGKILL)
                elif kind == "exit":
                    os._exit(int(fault.get("rc", 86)))
                elif kind == "hang":
                    time.sleep(float(fault.get("secs", 3600.0)))
                elif kind == "raise":
                    raise RuntimeError(
                        f"injected lane child fault [{tag} "
                        f"dispatch {idx}]")
        out = fn(*args)
        _send({"t": "hb", "phase": "done", "tag": tag, "n": idx,
               "depth": depth, "grace": idle_g})
        return out

    search._dispatch_hook = hook

    def on_lane(job_id, out, error, secs):
        if out is not None:
            _send({"t": "lane_result", "job_id": job_id,
                   "lane_secs": round(secs, 6),
                   "outcome": outcome_to_dict(out)})
        else:
            _send({"t": "lane_error", "job_id": job_id,
                   "error": error})

    try:
        res = search.run_lanes(jobs, resume=bool(spec.get("resume")),
                               swap=bool(spec.get("swap", True)),
                               on_lane=on_lane)
    except BaseException as e:  # noqa: BLE001 — reported over the pipe
        from dslabs_tpu.tpu.supervisor import CHILD_RC_FAILED

        _send({"t": "err", "error": f"{type(e).__name__}: {e}"[:500]})
        return CHILD_RC_FAILED
    finally:
        if child_tel is not None:
            child_tel.close()
    import jax

    _send({"t": "result", "swaps": res.swaps, "levels": res.levels,
           "dispatches": res.dispatches,
           "device_secs": round(res.device_secs, 6),
           "occupancy": res.occupancy,
           "platform": jax.devices()[0].platform})
    return 0


if __name__ == "__main__":
    sys.exit(_child_main())
