"""Generated lab 3 multi-Paxos: the hand twin
(tpu/protocols/paxos.py, now tests/fixtures/hand_twins/) rebuilt as a
:class:`~dslabs_tpu.tpu.compiler.ProtocolSpec` on the replicated-
protocol layer (ISSUE 20) — :class:`~dslabs_tpu.tpu.slots.Slots`
blocks for the per-slot log / P2b vote bitmaps / raw P1b votes, and a
declared majority :class:`~dslabs_tpu.tpu.quorum.QuorumCount` for the
phase-1/phase-2 counting.

Parity contract: every handler mirrors the hand twin (which mirrors
dslabs_tpu/labs/paxos/paxos.py handler-for-handler), message/timer
RECORDS are lane-identical (same tag order, same payload lane order,
same zero padding), and node state is a bijective lane PERMUTATION of
the hand layout (Slots lower struct-of-arrays, the hand twin
interleaved per-slot) — so unique-state counts are exactly preserved
while each lowered lane keeps its own packing domain.  That last part
is the point: the hand twin had NO ``lane_domains`` (identity codec on
the packed frontier); here every field declares ``lo``/``hi``, so lab3
finally rides the PR 15/18 bit-packing (ballot lanes cap at the hand
twin's ``_pack_entry`` 12-bit width — the same loud-overflow line, now
enforced by the packing layer instead of a hand guard).

Workload model (unchanged): ``n_clients`` clients each Put their own
key ``w`` times; command ids ``c * w + s`` (1-based), 0 = no-op.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.compiler import (Field, MessageType, NodeKind,
                                     ProtocolSpec, TimerType)
from dslabs_tpu.tpu.quorum import QuorumCount
from dslabs_tpu.tpu.slots import SlotField, Slots

__all__ = ["make_paxos_spec", "make_paxos_protocol",
           "make_paxos_partition_spec", "paxos_layout",
           "BALLOT_HI",
           "REQ", "P1A", "P1B", "P2A", "P2B", "HB", "HBR",
           "CREQ", "CREP", "REPLY",
           "T_ELECTION", "T_HEARTBEAT", "T_CLIENT"]

ELECTION_MIN, ELECTION_MAX = 150, 300
HEARTBEAT_MS = 50
CLIENT_MS = 100

# Message/timer tag enum mirrors the spec's declaration order — kept
# as module constants so adapters and tools can name wire rows without
# reaching into the compiled protocol.
REQ, P1A, P1B, P2A, P2B, HB, HBR, CREQ, CREP, REPLY = range(10)
T_ELECTION, T_HEARTBEAT, T_CLIENT = 1, 2, 3

# The hand twin's _pack_entry ballot width: ballots at or past this
# value are a loud overflow (there: EXC_PACK_WIDTH; here: the packed
# lane's declared domain) — never silent aliasing.
BALLOT_HI = (1 << 12) - 1


def make_paxos_spec(n: int = 3, n_clients: int = 1, w: int = 1,
                    max_slots: int = 2, net_cap: int = 64,
                    timer_cap: int = 8, fault=None) -> ProtocolSpec:
    S, NC = max_slots, n_clients
    cmd_hi = NC * w

    def cmd_id(client, seq):
        return client * w + seq        # 1-based; 0 = none/noop

    def cmd_client(cmd):
        return (cmd - 1) // w

    def cmd_seq(cmd):
        return (cmd - 1) % w + 1

    # ---- state: one Slots block per replicated structure ------------
    # Lane ORDER differs from the hand twin (struct-of-arrays vs the
    # hand interleave) — a bijective permutation, counts preserved.
    log = Slots("log", S, base=1, fields=(
        SlotField("ex", hi=1), SlotField("lb", hi=BALLOT_HI),
        SlotField("cmd", hi=cmd_hi), SlotField("ch", hi=1)))
    p2bv = Slots("p2bv", S, base=1, fields=(
        SlotField("v", hi=(1 << n) - 1),))
    # Raw P1b votes, one record per PEER: have flag + S packed-log
    # quadruples (the hand twin's votes [n, 1+4S] block).
    vote_fields = [SlotField("have",
                             init=lambda i, j: 1 if n == 1 else 0,
                             hi=1)]
    for s in range(1, S + 1):
        vote_fields += [SlotField(f"ex{s}", hi=1),
                        SlotField(f"lb{s}", hi=BALLOT_HI),
                        SlotField(f"cmd{s}", hi=cmd_hi),
                        SlotField(f"ch{s}", hi=1)]
    votes = Slots("votes", n, fields=tuple(vote_fields))

    server = NodeKind("server", n, (
        Field("b", init=1 if n == 1 else 0, hi=BALLOT_HI),
        Field("ld", init=1 if n == 1 else 0, hi=1),
        Field("hd", hi=1),
        Field("si", init=1, lo=1, hi=S + 1),
        Field("ex", hi=S), Field("cl", hi=S), Field("gc", hi=S),
        Field("pm", hi=(1 << n) - 1),
        Field("peer", size=n, hi=S, index_group="server"),
        Field("amo", size=NC, hi=w, index_group="client"),
        Field("prop", size=NC, hi=w, index_group="client"),
        p2bv, log, votes))
    client = NodeKind("client", NC, (Field("k", init=1, hi=w + 1),))

    # ---- message/timer enums: tag order and payload lane order are
    # the hand twin's (record-identical wire forms).
    e_hi = 3 + (BALLOT_HI << 2) + (cmd_hi << 14)
    bal = (0, BALLOT_HI)
    messages = [
        MessageType("Request", ("client", "seq"),
                    bounds={"client": (0, max(NC - 1, 0)),
                            "seq": (1, w)}),
        MessageType("P1a", ("b",), bounds={"b": bal}),
        MessageType("P1b", ("b",) + tuple(f"e{s}"
                                          for s in range(1, S + 1)),
                    bounds={"b": bal} | {f"e{s}": (0, e_hi)
                                         for s in range(1, S + 1)}),
        MessageType("P2a", ("b", "slot", "cmd"),
                    bounds={"b": bal, "slot": (1, S),
                            "cmd": (0, cmd_hi)}),
        MessageType("P2b", ("b", "slot"),
                    bounds={"b": bal, "slot": (1, S)}),
        MessageType("Heartbeat", ("b", "commit", "gc"),
                    bounds={"b": bal, "commit": (0, S), "gc": (0, S)}),
        MessageType("HeartbeatReply", ("b", "exec"),
                    bounds={"b": bal, "exec": (0, S)}),
        MessageType("CatchupRequest", ("slot",),
                    bounds={"slot": (1, S + 1)}),
        MessageType("CatchupReply",
                    ("base", "count") + tuple(f"c{s}"
                                              for s in range(1, S + 1)),
                    bounds={"base": (1, S + 1), "count": (0, S)}
                    | {f"c{s}": (0, cmd_hi) for s in range(1, S + 1)}),
        MessageType("Reply", ("client", "seq"),
                    bounds={"client": (0, max(NC - 1, 0)),
                            "seq": (1, w)}),
    ]
    timers = [
        TimerType("Election", (), min_ms=ELECTION_MIN,
                  max_ms=ELECTION_MAX),
        TimerType("Heartbeat", ("b",), min_ms=HEARTBEAT_MS,
                  max_ms=HEARTBEAT_MS, bounds={"b": bal}),
        TimerType("Client", ("k",), min_ms=CLIENT_MS, max_ms=CLIENT_MS,
                  bounds={"k": (1, w)}),
    ]

    spec = ProtocolSpec(
        name=f"paxos-n{n}-c{NC}-w{w}-s{S}",
        nodes=[server, client], messages=messages, timers=timers,
        net_cap=net_cap, timer_cap=timer_cap, fault=fault,
        quorums=(QuorumCount("servers", over="server",
                             threshold="majority"),))

    # ------------------------------------------------- shared helpers
    # Each mirrors the hand twin's helper of the same name; `ctx` is
    # already refined to the branch condition, `when` carries any extra.

    def pack_entry(ex, lb, cmd, ch):
        return ex | (ch << 1) | (lb << 2) | (cmd << 14)

    def unpack_entry(v):
        return v & 1, (v >> 2) & 0xFFF, v >> 14, (v >> 1) & 1

    def log_get(ctx, slot):
        return (ctx.slot_get("log", "ex", slot),
                ctx.slot_get("log", "lb", slot),
                ctx.slot_get("log", "cmd", slot),
                ctx.slot_get("log", "ch", slot))

    def log_set(ctx, slot, ex, lb, cmd, ch, when=True):
        ctx.slot_put("log", "ex", slot, ex, when=when)
        ctx.slot_put("log", "lb", slot, lb, when=when)
        ctx.slot_put("log", "cmd", slot, cmd, when=when)
        ctx.slot_put("log", "ch", slot, ch, when=when)

    def exec_chain(ctx):
        """Execute contiguous chosen slots (paxos.py _execute_chosen),
        sending client replies; leader updates its own peer_executed."""
        i = ctx.node_index()
        for _ in range(S):
            ex = ctx.get("ex")
            e_ex, _lb, cmd, e_ch = log_get(ctx, ex + 1)
            can = (ex + 1 <= S) & (e_ex == 1) & (e_ch == 1)
            ctx.put("ex", ex + 1, when=can)
            has_cmd = can & (cmd != 0)
            cl = cmd_client(cmd).clip(0, NC - 1)
            sq = cmd_seq(cmd)
            last = ctx.get_at("amo", cl)
            ctx.send("Reply", to=n + cl, when=has_cmd & (sq >= last),
                     client=cl, seq=sq)
            ctx.put_at("amo", cl, jnp.maximum(last, sq), when=has_cmd)
        is_leader = (ctx.get("ld") == 1) & (ctx.get("b") % n == i)
        ctx.put("pm", ctx.get("pm") | (1 << i), when=is_leader)
        ctx.put_at("peer", i, ctx.get("ex"), when=is_leader)
        maybe_gc(ctx, is_leader)

    def maybe_gc(ctx, when):
        mask = ctx.get("pm")
        floor = ctx.get_at("peer", 0)
        for j in range(1, n):
            floor = jnp.minimum(floor, ctx.get_at("peer", j))
        do = when & (mask == (1 << n) - 1) & (floor > ctx.get("gc"))
        ctx.put("gc", floor, when=do)
        gc_to(ctx, floor, do)

    def gc_to(ctx, through, when):
        through = jnp.minimum(through, ctx.get("ex"))
        do = when & (through > ctx.get("cl"))
        # Slots at or below the collective floor reset to their
        # cleared value — the slot-windowed garbage bound (slots below
        # `cl` are already cleared, so the wider window is idempotent).
        ctx.slot_clear_upto("log", through + 1, when=do)
        ctx.put("cl", through, when=do)

    def accept_p2a(ctx, ballot, slot, cmd, when=True):
        e_ex, _lb, _c, e_ch = log_get(ctx, slot)
        write = when & (slot > ctx.get("cl")) \
            & ~((e_ex == 1) & (e_ch == 1))
        log_set(ctx, slot, 1, ballot, cmd, 0, when=write)

    def send_p2a(ctx, slot):
        """Broadcast P2a for log[slot] + inline self-accept/self-vote
        (singleton groups complete the agreement in the same step)."""
        i = ctx.node_index()
        _ex, _lb, cmd, _ch = log_get(ctx, slot)
        ballot = ctx.get("b")
        for j in range(n):
            if j != i:
                ctx.send("P2a", to=j, b=ballot, slot=slot, cmd=cmd)
        accept_p2a(ctx, ballot, slot, cmd)
        ctx.put("hd", 1)
        e_ex, e_lb, _c, e_ch = log_get(ctx, slot)
        ok = (ctx.get("b") == ballot) & (e_ex == 1) & (e_ch == 0) \
            & (e_lb == ballot)
        ctx.slot_put("p2bv", "v", slot,
                     ctx.slot_get("p2bv", "v", slot) | (1 << i),
                     when=ok)
        if n == 1:
            e_ex, e_lb, e_cmd, e_ch = log_get(ctx, slot)
            ch = (e_ex == 1) & (e_ch == 0) & (e_lb == ballot)
            ctx.slot_put("p2bv", "v", slot, 0, when=ch)
            log_set(ctx, slot, 1, e_lb, e_cmd, 1, when=ch)
            exec_chain(ctx.cond(ch))

    def heartbeat_sends(ctx):
        i = ctx.node_index()
        for j in range(n):
            if j != i:
                ctx.send("Heartbeat", to=j, b=ctx.get("b"),
                         commit=ctx.get("ex"), gc=ctx.get("gc"))

    def p1b_win(ctx):
        """Phase-1 victory (handle_P1b body after majority); ctx is
        refined to the win condition."""
        i = ctx.node_index()
        ballot = ctx.get("b")
        ctx.put("ld", 1)
        ctx.put("p2bv.v", 0)
        ctx.put("pm", 1 << i)
        ctx.put("peer", jnp.where(jnp.arange(n) == i, ctx.get("ex"), 0))
        # Adoption: per slot, chosen wins; else max-ballot accepted.
        for s in range(1, S + 1):
            a_ex = jnp.zeros((), jnp.int32)
            a_b = jnp.full((), -1, jnp.int32)
            a_c = jnp.zeros((), jnp.int32)
            a_ch = jnp.zeros((), jnp.int32)
            for j in range(n):
                have = ctx.slot_get("votes", "have", j)
                ex = ctx.slot_get("votes", f"ex{s}", j)
                vb = ctx.slot_get("votes", f"lb{s}", j)
                vc = ctx.slot_get("votes", f"cmd{s}", j)
                vch = ctx.slot_get("votes", f"ch{s}", j)
                valid = (have == 1) & (ex == 1)
                take = valid & ((vch == 1) & (a_ch == 0)
                                | (a_ch == 0) & ((a_ex == 0)
                                                 | (vb > a_b)))
                a_b = jnp.where(take, vb, a_b)
                a_c = jnp.where(take, vc, a_c)
                a_ch = jnp.where(take, jnp.maximum(a_ch, vch), a_ch)
                a_ex = jnp.where(take, 1, a_ex)
            m_ex, _lb, _c, m_ch = log_get(ctx, s)
            adopt = (a_ex == 1) & (s > ctx.get("cl")) \
                & ~((m_ex == 1) & (m_ch == 1))
            log_set(ctx, s, 1, ballot, a_c, a_ch, when=adopt)
        # top = last non-empty; fill holes with no-ops; repropose
        # unchosen.
        top = ctx.get("cl")
        for s in range(1, S + 1):
            e_ex = ctx.slot_get("log", "ex", s)
            top = jnp.where(e_ex == 1, s, top)
        for s in range(1, S + 1):
            e_ex = ctx.slot_get("log", "ex", s)
            in_span = (s > ctx.get("ex")) & (s <= top)
            log_set(ctx, s, 1, ballot, 0, 0, when=in_span & (e_ex == 0))
            reprop = in_span & (ctx.slot_get("log", "ch", s) == 0)
            send_p2a(ctx.cond(reprop), s)
        ctx.put("si", top + 1)
        # proposed_seq from logged commands (max seq per client).
        for c in range(NC):
            best = jnp.zeros((), jnp.int32)
            for s in range(1, S + 1):
                e_ex, _lb, e_cmd, _ch = log_get(ctx, s)
                mine = (e_ex == 1) & (e_cmd != 0) \
                    & (cmd_client(e_cmd) == c)
                best = jnp.where(mine,
                                 jnp.maximum(best, cmd_seq(e_cmd)),
                                 best)
            ctx.put_at("prop", c, best)
        exec_chain(ctx)
        ctx.set_timer("Heartbeat", b=ballot)
        heartbeat_sends(ctx)

    # ----------------------------------------------- message handlers

    @spec.on("server", "Request")
    def srv_request(ctx, p):
        i = ctx.node_index()
        client, seq, frm = p["client"], p["seq"], p["_from"]
        b = ctx.get("b")
        ci = client.clip(0, NC - 1)
        last = ctx.get_at("amo", ci)
        already = seq <= last
        ctx.send("Reply", to=n + client,
                 when=already & (seq == last), client=client, seq=seq)
        is_leader = (ctx.get("ld") == 1) & (b % n == i)
        believed = b % n
        ctx.send("Request", to=believed,
                 when=~already & ~is_leader & ((frm == i) | (frm >= n))
                 & (believed != i), client=client, seq=seq)
        prop = ctx.get_at("prop", ci)
        slot = ctx.get("si")
        do_prop = ~already & is_leader & (seq > prop) & (slot <= S)
        ctx.put_at("prop", ci, seq, when=do_prop)
        ctx.put("si", slot + 1, when=do_prop)
        pctx = ctx.cond(do_prop)
        log_set(pctx, slot, 1, b, cmd_id(client, seq), 0)
        send_p2a(pctx, slot)

    @spec.on("server", "P1a")
    def srv_p1a(ctx, p):
        mb, frm = p["b"], p["_from"]
        adopt = mb > ctx.get("b")
        ctx.put("b", mb, when=adopt)
        ctx.put("ld", 0, when=adopt)
        ctx.send("P1b", to=frm, when=mb == ctx.get("b"),
                 b=ctx.get("b"),
                 **{f"e{s}": pack_entry(*log_get(ctx, s))
                    for s in range(1, S + 1)})

    @spec.on("server", "P1b")
    def srv_p1b(ctx, p):
        i = ctx.node_index()
        vb, frm = p["b"], p["_from"]
        accept_vote = (vb == ctx.get("b")) & (ctx.get("b") % n == i) \
            & (ctx.get("ld") == 0)
        ctx.slot_put("votes", "have", frm, 1, when=accept_vote)
        for s in range(1, S + 1):
            ex, lb, cmd, ch = unpack_entry(p[f"e{s}"])
            ctx.slot_put("votes", f"ex{s}", frm, ex, when=accept_vote)
            ctx.slot_put("votes", f"lb{s}", frm, lb, when=accept_vote)
            ctx.slot_put("votes", f"cmd{s}", frm, cmd,
                         when=accept_vote)
            ctx.slot_put("votes", f"ch{s}", frm, ch, when=accept_vote)
        q = ctx.quorum("servers")
        win = accept_vote & q.met(ctx.get("votes.have"))
        p1b_win(ctx.cond(win))

    @spec.on("server", "P2a")
    def srv_p2a(ctx, p):
        ab, aslot, acmd, frm = p["b"], p["slot"], p["cmd"], p["_from"]
        ok = ab >= ctx.get("b")
        ctx.put("ld", 0, when=ok & (ab > ctx.get("b")))
        ctx.put("b", ab, when=ok)
        ctx.put("hd", 1, when=ok)
        accept_p2a(ctx, ab, aslot, acmd, when=ok)
        ctx.send("P2b", to=frm, when=ok, b=ab, slot=aslot)

    @spec.on("server", "P2b")
    def srv_p2b(ctx, p):
        i = ctx.node_index()
        bb, bslot, frm = p["b"], p["slot"], p["_from"]
        lead_ok = (bb == ctx.get("b")) & (ctx.get("ld") == 1) \
            & (ctx.get("b") % n == i)
        e_ex, e_lb, e_cmd, e_ch = log_get(ctx, bslot)
        count_ok = lead_ok & (e_ex == 1) & (e_ch == 0) & (e_lb == bb)
        vmask = ctx.slot_get("p2bv", "v", bslot)
        vmask2 = jnp.where(count_ok,
                           vmask | (1 << frm.clip(0, n - 1)), vmask)
        q = ctx.quorum("servers")
        chosen_now = count_ok & q.met_bits(vmask2)
        ctx.slot_put("p2bv", "v", bslot,
                     jnp.where(chosen_now, 0, vmask2), when=count_ok)
        log_set(ctx, bslot, 1, e_lb, e_cmd, 1, when=chosen_now)
        exec_chain(ctx.cond(chosen_now))

    @spec.on("server", "Heartbeat")
    def srv_heartbeat(ctx, p):
        hb_b, hb_commit, hb_gc = p["b"], p["commit"], p["gc"]
        frm = p["_from"]
        ok = hb_b >= ctx.get("b")
        ctx.put("ld", 0, when=ok & (hb_b > ctx.get("b")))
        ctx.put("b", hb_b, when=ok)
        ctx.put("hd", 1, when=ok)
        gc_to(ctx, hb_gc, ok)
        ctx.send("CatchupRequest", to=frm,
                 when=ok & (ctx.get("ex") < hb_commit),
                 slot=ctx.get("ex") + 1)
        ctx.send("HeartbeatReply", to=frm, when=ok, b=ctx.get("b"),
                 exec=ctx.get("ex"))

    @spec.on("server", "HeartbeatReply")
    def srv_heartbeat_reply(ctx, p):
        i = ctx.node_index()
        rb, rexec, frm = p["b"], p["exec"], p["_from"]
        ok = (rb == ctx.get("b")) & (ctx.get("ld") == 1) \
            & (ctx.get("b") % n == i)
        pcur = ctx.get_at("peer", frm)
        ctx.put_at("peer", frm, jnp.maximum(pcur, rexec), when=ok)
        ctx.put("pm", ctx.get("pm") | (1 << frm.clip(0, n - 1)),
                when=ok)
        maybe_gc(ctx, ok)

    @spec.on("server", "CatchupRequest")
    def srv_catchup_request(ctx, p):
        frm = p["_from"]
        from_slot = jnp.maximum(p["slot"], ctx.get("cl") + 1)
        cmds = {}
        count = jnp.zeros((), jnp.int32)
        contiguous = jnp.asarray(True)
        for k in range(S):
            slot = from_slot + k
            e_ex, _lb, e_cmd, e_ch = log_get(ctx, slot)
            ok = contiguous & (slot <= ctx.get("ex")) & (e_ex == 1) \
                & (e_ch == 1)
            contiguous = ok
            cmds[f"c{k + 1}"] = jnp.where(ok, e_cmd, 0)
            count = count + ok.astype(jnp.int32)
        ctx.send("CatchupReply", to=frm, when=count > 0,
                 base=from_slot, count=count, **cmds)

    @spec.on("server", "CatchupReply")
    def srv_catchup_reply(ctx, p):
        base, ccount = p["base"], p["count"]
        for k in range(S):
            slot = base + k
            e_ex, _lb, _c, e_ch = log_get(ctx, slot)
            install = (k < ccount) & (slot > ctx.get("cl")) \
                & ~((e_ex == 1) & (e_ch == 1))
            log_set(ctx, slot, 1, ctx.get("b"), p[f"c{k + 1}"], 1,
                    when=install)
        exec_chain(ctx)

    @spec.on("client", "Reply")
    def cli_reply(ctx, p):
        c = ctx.node_index() - n
        k = ctx.get("k")
        match = (p["client"] == c) & (p["seq"] == k) & (k <= w)
        k2 = jnp.where(match, k + 1, k)
        ctx.put("k", k2)
        has_next = match & (k2 <= w)
        for j in range(n):
            ctx.send("Request", to=j, when=has_next, client=c, seq=k2)
        ctx.set_timer("Client", when=has_next, k=k2)

    # ------------------------------------------------- timer handlers

    @spec.on_timer("server", "Election")
    def srv_election(ctx, p):
        i = ctx.node_index()
        b = ctx.get("b")
        is_leader = (ctx.get("ld") == 1) & (b % n == i)
        elect = ~is_leader & (ctx.get("hd") == 0)
        new_ballot = (b // n + 1) * n + i
        ctx.put("b", new_ballot, when=elect)
        ctx.put("ld", 0, when=elect)
        for sf in votes.fields:
            ctx.put(votes.lane(sf.name), 0, when=elect)
        for j in range(n):
            if j != i:
                ctx.send("P1a", to=j, when=elect, b=new_ballot)
        # Self-promise: own vote with own log (P1a -> P1b
        # self-delivery).
        ectx = ctx.cond(elect)
        ectx.slot_put("votes", "have", i, 1)
        for s in range(1, S + 1):
            e_ex, e_lb, e_cmd, e_ch = log_get(ectx, s)
            ectx.slot_put("votes", f"ex{s}", i, e_ex)
            ectx.slot_put("votes", f"lb{s}", i, e_lb)
            ectx.slot_put("votes", f"cmd{s}", i, e_cmd)
            ectx.slot_put("votes", f"ch{s}", i, e_ch)
        if n == 1:
            # Singleton group: our own vote IS the majority — the
            # object server wins phase 1 inside the same ElectionTimer
            # handler, so the generated twin fires the win cascade here
            # (it arms the leader heartbeat itself).
            p1b_win(ectx)
        ctx.put("hd", 0)
        ctx.set_timer("Election")

    @spec.on_timer("server", "Heartbeat")
    def srv_heartbeat_timer(ctx, p):
        i = ctx.node_index()
        live = (p["b"] == ctx.get("b")) & (ctx.get("ld") == 1) \
            & (ctx.get("b") % n == i)
        lctx = ctx.cond(live)
        heartbeat_sends(lctx)
        for s in range(1, S + 1):
            e_ex = ctx.slot_get("log", "ex", s)
            e_ch = ctx.slot_get("log", "ch", s)
            inflight = live & (s > ctx.get("ex")) \
                & (s < ctx.get("si")) & (e_ex == 1) & (e_ch == 0)
            send_p2a(ctx.cond(inflight), s)
        ctx.set_timer("Heartbeat", when=live, b=p["b"])

    @spec.on_timer("client", "Client")
    def cli_timer(ctx, p):
        c = ctx.node_index() - n
        k = ctx.get("k")
        live = (p["k"] == k) & (k <= w)
        for j in range(n):
            ctx.send("Request", to=j, when=live, client=c, seq=k)
        ctx.set_timer("Client", when=live, k=k)

    # -------------------------------------------- initials/predicates

    for c in range(NC):
        for j in range(n):
            spec.initial_messages.append(
                ("Request", n + c, j, {"client": c, "seq": 1}))
    for i in range(n):
        spec.initial_timers.append(("Election", i, {}))
        if n == 1:
            # A lone server self-elects SYNCHRONOUSLY at init (the
            # object never spends an ElectionTimer event becoming
            # leader); its win cascade armed the heartbeat, so the root
            # timer queue is [Election, Heartbeat].
            spec.initial_timers.append(("Heartbeat", i, {"b": 1}))
    for c in range(NC):
        spec.initial_timers.append(("Client", n + c, {"k": 1}))

    def clients_done(view):
        done = jnp.asarray(True)
        for c in range(NC):
            done = done & (view.get("client", c, "k") == w + 1)
        return done

    def logs_consistent(view):
        """slotValid core: no two different commands chosen in a
        slot."""
        ok = jnp.asarray(True)
        for s in range(1, S + 1):
            chosen_cmd = jnp.full((), -1, jnp.int32)
            seen = jnp.zeros((), jnp.int32)
            bad = jnp.asarray(False)
            for i in range(n):
                e0 = view.get("server", i, "log.ex")[s - 1]
                ech = view.get("server", i, "log.ch")[s - 1]
                ec = view.get("server", i, "log.cmd")[s - 1]
                is_ch = (e0 == 1) & (ech == 1)
                bad = bad | (is_ch & (seen == 1) & (ec != chosen_cmd))
                chosen_cmd = jnp.where(is_ch, ec, chosen_cmd)
                seen = jnp.where(is_ch, 1, seen)
            ok = ok & ~bad
        return ok

    spec.goals["CLIENTS_DONE"] = clients_done
    spec.invariants["LOGS_CONSISTENT"] = logs_consistent
    return spec


def make_paxos_protocol(n: int = 3, n_clients: int = 1, w: int = 1,
                        max_slots: int = 2, net_cap: int = 64,
                        timer_cap: int = 8, fault=None):
    """Drop-in replacement for the deleted hand twin's factory: same
    signature, same protocol name, same searched state space (exact
    pinned-count parity) — now compiled from the spec."""
    return make_paxos_spec(n, n_clients, w, max_slots, net_cap,
                           timer_cap, fault=fault).compile()


def make_paxos_partition_spec(n: int = 3, n_clients: int = 1,
                              w: int = 1, max_slots: int = 2,
                              net_cap: int = 64,
                              timer_cap: int = 8) -> ProtocolSpec:
    """The generated multi-decree paxos under a one-era partition
    scenario (ISSUE 19 model events on the ISSUE 20 spec layer): the
    last server is isolated from the rest until the heal.  CUT/HEAL
    interleave with protocol events as ordinary model transitions, so
    leader elections that straddle the cut are explored exhaustively;
    the clients are never cut off."""
    from dslabs_tpu.tpu.faults import FaultModel, Partition

    fm = FaultModel(partition=Partition(blocks=(
        tuple(("server", i) for i in range(n - 1)),
        (("server", n - 1),)), max_eras=1))
    spec = make_paxos_spec(n, n_clients, w, max_slots, net_cap,
                           timer_cap, fault=fm)
    spec.name += "-part"
    return spec


def paxos_layout(n: int, n_clients: int, max_slots: int) -> dict:
    """Per-server lane offsets of the GENERATED node vector, for the
    harness backend's lane predicates (tpu/adapters/paxos.py).  Keys
    name spec fields; "SW"/"NW"/"N_NODES" mirror the old hand-layout
    helper so adapter arithmetic stays one lookup away from the spec."""
    spec = make_paxos_spec(n, n_clients, max_slots=max_slots)
    table, nw = spec._layout()
    offs = {f: off for (kind, i, f), (off, _s)
            in table.items() if kind == "server" and i == 0}
    sw = (table[("server", 1, "b")][0] if n > 1
          else max(off + s for (k, _i, _f), (off, s) in table.items()
                   if k == "server"))
    cli0 = table[("client", 0, "k")][0]
    return offs | {"SW": sw, "NW": nw, "N_NODES": n + n_clients,
                   "CLI0": cli0}
