"""Lab 3 multi-Paxos twin adapter: object search configurations ->
tensor twin bindings for the harness search backend (tpu/backend.py).

Recognises a ``SearchState`` whose servers are all ``PaxosServer`` and
whose client workers drive ``PaxosClient`` with finite KV workloads, and
binds it to ``make_paxos_protocol`` with:

- twin node indices: ``server{i+1}`` -> i, ``client{c+1}`` -> n + c
  (the parity-test naming, tests/test_tpu_engine.py);
- command ids: client ``c``'s k-th workload command (1-based seq) ->
  ``c * w + k`` (the twin's ``cmd_id``); 0 = the no-op hole filler;
- lane predicates for the lab 3 predicate library (log statuses and
  consistency mirror PaxosServer.status/command semantics,
  labs/paxos/paxos.py:210-233, on the packed lanes of
  ``paxos_layout``);
- object decoders for trace replay (tpu/trace.py): every tensor message
  record maps to the exact object Message — the twin models every field
  except the ``PaxosReply`` RESULT VALUE, which is resolved from the
  replayed object state's own network via a MessageTemplate (the object
  execution is the source of truth for application values).

**Value-collapse argument** (why result-blind lanes give the same
verdicts): client workloads are sequential, so a client's k-th result is
produced by executing the agreed log prefix up to its command's slot —
a deterministic function of lanes the twin DOES model (log contents +
executed_through + per-client seq).  ``RESULTS_OK``-class predicates can
therefore only fire on states whose log/exec lanes already differ, and
on this repo's (correct) lab 3 implementation they fire on neither
backend.  The bounded-depth parity tests (tests/test_search_backend.py)
pin the unique-state counts of both backends against each other under
the actual lab settings, which is what guards this argument in CI.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

import numpy as np

from dslabs_tpu.tpu.backend import (NoTensorTwin, TwinBinding,
                                    register_adapter)

__all__ = ["PaxosBinding"]


def _workload_pairs(worker, addr):
    wl = copy.deepcopy(worker.workload)
    wl.reset()
    if wl.infinite():
        raise NoTensorTwin("infinite workloads have no tensor twin")
    return [wl._next_pair(addr) for _ in range(wl.size())]


def _num_suffix(name: str, prefix: str) -> Optional[int]:
    if not name.startswith(prefix):
        return None
    try:
        return int(name[len(prefix):])
    except ValueError:
        return None


class PaxosBinding(TwinBinding):

    def __init__(self, state):
        from dslabs_tpu.tpu.specs_lab3 import paxos_layout

        servers = sorted(state.servers,
                         key=lambda a: _num_suffix(str(a), "server") or 0)
        clients = sorted(state.client_workers(),
                         key=lambda a: _num_suffix(str(a), "client") or 0)
        self.n = len(servers)
        self.nc = len(clients)
        self.server_names = [str(a) for a in servers]
        self.client_names = [str(a) for a in clients]
        self.addr_index = {s: i for i, s in enumerate(self.server_names)}
        self.addr_index.update(
            {c: self.n + j for j, c in enumerate(self.client_names)})
        workers = state.client_workers()
        pairs = [_workload_pairs(workers[a], a) for a in clients]
        sizes = {len(p) for p in pairs}
        if len(sizes) != 1:
            raise NoTensorTwin(
                f"per-client workload sizes differ ({sizes}); the twin "
                "models a uniform per-client command count")
        self.w = sizes.pop()
        self.S = self.w * self.nc
        # command object -> twin cmd ids (clients may send EQUAL raw
        # commands — each occurrence has its own id; has_command matches
        # any of them, exactly the object predicate's equality)
        self.cmd_ids: Dict[object, list] = {}
        self.cmd_objs: Dict[int, object] = {}
        self.results: Dict[int, object] = {}
        for c, plist in enumerate(pairs):
            for k, (cmd, res) in enumerate(plist, start=1):
                cid = c * self.w + k
                self.cmd_ids.setdefault(cmd, []).append(cid)
                self.cmd_objs[cid] = cmd
                if res is not None:
                    self.results[cid] = res
        self.L = paxos_layout(self.n, self.nc, self.S)
        self.key = ("paxos", self.n, self.nc, self.w, self.S,
                    tuple(self.server_names), tuple(self.client_names),
                    tuple(repr(self.cmd_objs[i])
                          for i in sorted(self.cmd_objs)))

    def initial_caps(self):
        return 32, 6

    # ------------------------------------------------------------ protocol

    def build_protocol(self, net_cap, timer_cap):
        import dataclasses

        from dslabs_tpu.tpu.specs_lab3 import make_paxos_protocol

        p = make_paxos_protocol(n=self.n, n_clients=self.nc, w=self.w,
                                max_slots=self.S, net_cap=net_cap,
                                timer_cap=timer_cap)
        return dataclasses.replace(
            p, decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    # ------------------------------------------------------------ decoders

    def _addr(self, idx: int):
        from dslabs_tpu.core.address import LocalAddress

        names = self.server_names + self.client_names
        return LocalAddress(names[int(idx)])

    def _ballot(self, b: int):
        return (int(b) // self.n, int(b) % self.n)

    def _amo(self, cid: int):
        from dslabs_tpu.labs.clientserver.amo import AMOCommand

        cid = int(cid)
        c, k = (cid - 1) // self.w, (cid - 1) % self.w + 1
        from dslabs_tpu.core.address import LocalAddress

        return AMOCommand(self.cmd_objs[cid],
                          LocalAddress(self.client_names[c]), k)

    def _decode_message(self, rec):
        from dslabs_tpu.labs.clientserver.amo import AMOResult
        from dslabs_tpu.labs.paxos import paxos as P
        from dslabs_tpu.tpu.specs_lab3 import (CREP, CREQ, HB, HBR,
                                                    P1A, P1B, P2A, P2B,
                                                    REPLY, REQ)
        from dslabs_tpu.tpu.trace import MessageTemplate

        r = [int(x) for x in rec]
        tag, frm, to, p = r[0], r[1], r[2], r[3:]
        fa, ta = self._addr(frm), self._addr(to)
        if tag == REQ:
            return fa, ta, P.PaxosRequest(self._amo(p[0] * self.w + p[1]))
        if tag == REPLY:
            cid = p[0] * self.w + p[1]
            seq = (cid - 1) % self.w + 1
            fallback = P.PaxosReply(AMOResult(self.results.get(cid), seq))
            return fa, ta, MessageTemplate(
                P.PaxosReply, fallback,
                lambda m, s=seq: m.result.sequence_num == s)
        if tag == P1A:
            return fa, ta, P.P1a(self._ballot(p[0]))
        if tag == P1B:
            entries = []
            for s in range(1, self.S + 1):
                ex, lb, cmd, ch = _unpack(p[s])
                if ex:
                    entries.append(
                        (s, (self._ballot(lb),
                             self._amo(cmd) if cmd else None, bool(ch))))
            return fa, ta, P.P1b(self._ballot(p[0]), tuple(entries))
        if tag == P2A:
            return fa, ta, P.P2a(self._ballot(p[0]), p[1],
                                 self._amo(p[2]) if p[2] else None)
        if tag == P2B:
            return fa, ta, P.P2b(self._ballot(p[0]), p[1])
        if tag == HB:
            return fa, ta, P.Heartbeat(self._ballot(p[0]), p[1], p[2])
        if tag == HBR:
            return fa, ta, P.HeartbeatReply(self._ballot(p[0]), p[1])
        if tag == CREQ:
            return fa, ta, P.CatchupRequest(p[0])
        if tag == CREP:
            base, count = p[0], p[1]
            ents = tuple(
                (base + k, self._amo(p[2 + k]) if p[2 + k] else None)
                for k in range(count))
            return fa, ta, P.CatchupReply(ents)
        raise NoTensorTwin(f"unknown paxos message tag {tag}")

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.labs.paxos import paxos as P
        from dslabs_tpu.tpu.specs_lab3 import (CLIENT_MS,
                                                    ELECTION_MAX,
                                                    ELECTION_MIN,
                                                    HEARTBEAT_MS,
                                                    T_CLIENT, T_ELECTION,
                                                    T_HEARTBEAT)

        tag, p0 = int(rec[0]), int(rec[3])
        a = self._addr(node_idx)
        if tag == T_ELECTION:
            return a, P.ElectionTimer(), ELECTION_MIN, ELECTION_MAX
        if tag == T_HEARTBEAT:
            return (a, P.HeartbeatTimer(self._ballot(p0)), HEARTBEAT_MS,
                    HEARTBEAT_MS)
        if tag == T_CLIENT:
            return a, P.ClientTimer(p0), CLIENT_MS, CLIENT_MS
        raise NoTensorTwin(f"unknown paxos timer tag {tag}")

    # ---------------------------------------------------------- predicates

    def _lane(self, s, i, off):
        return s["nodes"][i * self.L["SW"] + off]

    def _log(self, s, i, slot, j):
        # The compiled layout is field-major: each log field owns S
        # consecutive lanes (j: 0=ex, 1=lb, 2=cmd, 3=ch).
        key = ("log.ex", "log.lb", "log.cmd", "log.ch")[j]
        return s["nodes"][i * self.L["SW"] + self.L[key] + (slot - 1)]

    def _k(self, s, c):
        return s["nodes"][self.n * self.L["SW"] + c]

    def _statuses(self, s, slot):
        """Per-server (cleared, empty, accepted, chosen, cmd) lane bools
        for one slot, mirroring PaxosServer.status/command
        (labs/paxos/paxos.py:210-226)."""
        out = []
        for i in range(self.n):
            cl = self._lane(s, i, 5)
            ex = self._log(s, i, slot, 0) == 1
            ch = self._log(s, i, slot, 3) == 1
            cmd = self._log(s, i, slot, 2)
            cleared = slot <= cl
            out.append((cleared, ~cleared & ~ex, ~cleared & ex & ~ch,
                        ~cleared & ex & ch, cmd))
        return out

    def _slot_valid(self, s, slot):
        """slotValid's live checks on lanes (the status-vs-marker
        consistency checks are definitionally true on the twin): no two
        different chosen commands, and chosen/cleared only with a
        majority accepting (labs/paxos/predicates.py:47-82)."""
        import jax.numpy as jnp

        st = self._statuses(s, slot)
        any_chosen = jnp.asarray(False)
        any_cleared = jnp.asarray(False)
        conflict = jnp.asarray(False)
        chosen_cmd = jnp.full((), -1, np.int32)
        for cleared, empty, acc, ch, cmd in st:
            conflict = conflict | (ch & any_chosen & (cmd != chosen_cmd))
            chosen_cmd = jnp.where(ch, cmd, chosen_cmd)
            any_chosen = any_chosen | ch
            any_cleared = any_cleared | cleared
        count = jnp.zeros((), np.int32)
        for cleared, empty, acc, ch, cmd in st:
            ok = ~empty & (~acc | ~any_chosen | (cmd == chosen_cmd))
            count = count + ok.astype(np.int32)
        quorum = (~(any_chosen | any_cleared)
                  | (2 * count > self.n))
        return ~conflict & quorum

    def predicate(self, tkey):
        import jax.numpy as jnp

        kind = tkey[0]
        n, w, S = self.n, self.w, self.S

        def const_true(s):
            # Structurally-true on the twin (see the module docstring's
            # value-collapse argument); tied to a lane so the engine's
            # vmap sees a batched output.
            return self._k(s, 0) >= 0

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME", "PAXOS_MARKERS_VALID"):
            # value_level marks predicates the twin cannot falsify — the
            # backend re-checks them object-side on sampled deepest
            # states before trusting an exhaust verdict
            # (backend.tensor_bfs).  Marked ONLY here, not on the shared
            # const_true closure: the out-of-range structural uses below
            # are true on both twins by construction and need no replay.
            fn = lambda s: const_true(s)     # noqa: E731
            fn.value_level = True
            return fn
        if kind == "CLIENTS_DONE":
            def fn(s):
                done = jnp.asarray(True)
                for c in range(self.nc):
                    done = done & (self._k(s, c) == w + 1)
                return done
            return fn
        if kind == "NONE_DECIDED":
            def fn(s):
                nd = jnp.asarray(True)
                for c in range(self.nc):
                    nd = nd & (self._k(s, c) == 1)
                return nd
            return fn
        if kind == "CLIENT_DONE":
            c = self.client_names.index(str(tkey[1].root_address()))
            return lambda s: self._k(s, c) == w + 1
        if kind == "CLIENT_HAS_RESULTS":
            c = self.client_names.index(str(tkey[1].root_address()))
            num = tkey[2]
            return lambda s: self._k(s, c) >= num + 1
        if kind == "PAXOS_SLOT_VALID":
            slot = tkey[1]
            if not 1 <= slot <= S:
                return const_true       # out-of-range slots stay EMPTY
            return lambda s: self._slot_valid(s, slot)
        if kind == "PAXOS_LOGS_CONSISTENT":
            all_slots = tkey[1]

            def fn(s):
                ok = jnp.asarray(True)
                if not all_slots:
                    min_nc = self._lane(s, 0, 5)
                    for i in range(1, n):
                        min_nc = jnp.minimum(min_nc, self._lane(s, i, 5))
                    min_nc = min_nc + 1
                for slot in range(1, S + 1):
                    v = self._slot_valid(s, slot)
                    if not all_slots:
                        v = v | (jnp.asarray(slot) < min_nc)
                    ok = ok & v
                return ok
            return fn
        if kind == "PAXOS_HAS_STATUS":
            i = self.server_names.index(str(tkey[1].root_address()))
            slot, status = tkey[2], tkey[3]
            if not 1 <= slot <= S:
                if status == "EMPTY":
                    return const_true
                return lambda s: ~const_true(s)

            def fn(s):
                cleared, empty, acc, ch, _ = self._statuses(s, slot)[i]
                return {"CLEARED": cleared, "EMPTY": empty,
                        "ACCEPTED": acc, "CHOSEN": ch}[status]
            return fn
        if kind == "PAXOS_HAS_COMMAND":
            i = self.server_names.index(str(tkey[1].root_address()))
            slot, cmd = tkey[2], tkey[3]
            cids = self.cmd_ids.get(cmd)
            if not cids or not 1 <= slot <= S:
                # A command no client ever sends (or an out-of-range
                # slot) can never be in a log: constant false, exactly
                # the object predicate's value.
                return lambda s: ~const_true(s)

            def fn(s):
                cl = self._lane(s, i, 5)
                ex = self._log(s, i, slot, 0) == 1
                c = self._log(s, i, slot, 2)
                hit = jnp.asarray(False)
                for cid in cids:
                    hit = hit | (c == cid)
                return (jnp.asarray(slot) > cl) & ex & hit
            return fn
        return None


def _unpack(packed: int):
    """Inverse of the twin's packed log-entry bit layout (the
    tpu/specs_lab3.py Slots lowering, kept in lockstep)."""
    v = int(packed)
    return v & 1, (v >> 2) & 0xFFF, v >> 14, (v >> 1) & 1


@register_adapter
def match_paxos(state):
    from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer

    servers = state.servers
    workers = state.client_workers()
    if not servers or not workers:
        return None
    if not all(isinstance(s, PaxosServer) for s in servers.values()):
        return None
    if not all(isinstance(wk.client, PaxosClient)
               for wk in workers.values()):
        return None
    return PaxosBinding(state)
