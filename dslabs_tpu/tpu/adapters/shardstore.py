"""Lab 4 twin adapters for the harness search backend (tpu/backend.py).

Lab 4's search tests are TWO-phase (ShardStoreBaseTest.java:209-220 via
tests/test_lab4_shardstore.py):

1. The JOIN phase: the config controller (a PaxosClient ClientWorker)
   drives G Join commands through the shard master, with every store
   server cut off.  :class:`JoinBinding` runs it on the generated
   join twin (tpu/specs_lab4.py make_join_protocol).
2. The MAIN phase: staged from the join goal state, a ShardStoreClient
   worker drives a KV workload through the store groups.
   :class:`ShardStoreBinding` runs it on the generated shardstore
   twin (tpu/specs_lab4.py make_shardstore_protocol), whose initial
   state BAKES IN the
   staged joins — so ``derive_root`` VALIDATES that the staged object
   state is the canonical joined root (every deviation is a loud
   NoTensorTwin) instead of replaying provenance.  This also lets
   object-staged roots (no tensor provenance) seed tensor searches.

Both bindings re-check what the twins value-collapse: app results
resolve from the replayed object state's network via MessageTemplate,
and RESULTS_OK-class invariants are marked ``value_level`` so the
backend's sampled exhaust re-check covers them object-side.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from dslabs_tpu.tpu.adapters.paxos import _workload_pairs
from dslabs_tpu.tpu.backend import (NoTensorTwin, TwinBinding,
                                    register_adapter)

__all__ = ["JoinBinding", "ShardStoreBinding"]

PAXOS_ID = "paxos"


def _single(seq, what: str):
    items = list(seq)
    if len(items) != 1:
        raise NoTensorTwin(
            f"shardstore twin models exactly one {what} "
            f"(found {len(items)})")
    return items[0]


def _ctl_live(settings, ctl_names, master_name):
    """Controller addresses whose events are deliverable under the FULL
    should_deliver precedence (link override -> sender -> receiver ->
    network, testing/settings.py:138-151) or whose timers are live."""
    from dslabs_tpu.core.address import LocalAddress

    snd = {str(a): v for a, v in settings._sender_active.items()}
    rcv = {str(a): v for a, v in settings._receiver_active.items()}
    link = {(str(f), str(t)): v
            for (f, t), v in settings._link_active.items()}

    def msg_live(f, t):
        v = link.get((f, t))
        if v is None:
            v = snd.get(f)
        if v is None:
            v = rcv.get(t)
        if v is None:
            v = settings._network_active
        return v

    return [n for n in ctl_names
            if (settings.should_deliver_timer(LocalAddress(n))
                or msg_live(n, master_name)
                or msg_live(master_name, n))]


def _validate_joined_root(state, master_name, server_names,
                          client_names) -> None:
    """Shared canonical-joined-root validation: the lab4 twins' initial
    states BAKE IN the staged joins, so instead of provenance replay the
    bindings verify the staged object state matches that canonical shape
    field by field — any deviation is a loud NoTensorTwin, never a
    silently-wrong root."""
    from dslabs_tpu.core.address import LocalAddress

    def req(cond, what):
        if not cond:
            raise NoTensorTwin(
                f"staged state is not the canonical joined root: {what}")

    by_name = {str(a): s for a, s in state.servers.items()}
    master = by_name[master_name]
    app = master.app
    for name in (*client_names, *server_names):
        req(app.last.get(LocalAddress(name)) is None,
            f"master AMO already has an entry for {name}")
    for name in server_names:
        s = by_name[name]
        req(s.current_config is None, f"{name} already has a config")
        req(s.qseq == 0, f"{name} qseq {s.qseq} != 0")
        req(not s.owned and not s.incoming and not s.outgoing,
            f"{name} has shard-handoff state")
        req(not s.locks and not s.prepared and not s.coord,
            f"{name} has 2PC state")
        req(not s.paxos.log, f"{name} paxos log not empty")
    workers = {str(a): w for a, w in state.client_workers().items()}
    for name in client_names:
        worker = workers[name]
        req(not worker.results, f"{name} already has results")
        c = worker.client
        req(c.current_config is None, f"{name} already has a config")
        req(c.qseq == 2, f"{name} qseq {c.qseq} != 2 (init + "
            "config-less send_pending fallback)")
        req(c.pending is not None and c.pending.sequence_num == 1,
            f"{name}'s first command is not pending")


class JoinBinding(TwinBinding):
    """Join-phase binding: one shard master + the config controller,
    store servers cut off (tpu/specs_lab4.py make_join_protocol)."""

    def __init__(self, state, master_addr, worker_addr, store_addrs):
        from dslabs_tpu.labs.shardedstore.shardmaster import Join, Ok

        self.master_name = str(master_addr)
        self.client_name = str(worker_addr)
        self.store_names = [str(a) for a in store_addrs]
        self.addr_index = {self.master_name: 0, self.client_name: 1}
        worker = state.client_workers()[worker_addr]
        pairs = _workload_pairs(worker, worker_addr)
        for cmd, res in pairs:
            if not isinstance(cmd, Join):
                raise NoTensorTwin(
                    f"join twin models Join workloads only, got {cmd!r}")
            if res is not None and not isinstance(res, Ok):
                raise NoTensorTwin(
                    f"join twin expects Ok results, got {res!r}")
        self.pairs = pairs
        self.w = len(pairs)
        # The master's post-init self-election ballot (constant for a
        # lone server: paxos.py:261-265 never re-elects a leader whose
        # ballot is its own) — recorded for HeartbeatTimer decode.
        self.master_ballot = state.servers[master_addr].ballot
        self.key = ("ss-join", self.master_name, self.client_name,
                    tuple(repr(c) for c, _ in pairs))

    def initial_caps(self):
        return 12, 4

    def check_settings(self, settings) -> None:
        from dslabs_tpu.core.address import LocalAddress

        for name in self.store_names:
            if settings.should_deliver_timer(LocalAddress(name)):
                raise NoTensorTwin(
                    f"join twin does not model store server {name}; its "
                    "timers must be suppressed "
                    "(settings.deliver_timers(addr, False))")

    def build_protocol(self, net_cap, timer_cap):
        from dslabs_tpu.tpu.specs_lab4 import make_join_protocol

        # net_cap passes through unchanged so the capacity ladder's
        # doubling (net_cap << attempt) actually escalates this twin.
        p = make_join_protocol(self.w, net_cap=max(net_cap, 12),
                               timer_cap=max(timer_cap, 4))
        return dataclasses.replace(
            p, decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    # ------------------------------------------------------------ decoders

    def _decode_message(self, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.amo import AMOCommand, AMOResult
        from dslabs_tpu.labs.paxos.paxos import PaxosReply, PaxosRequest
        from dslabs_tpu.tpu.specs_lab4 import JOIN_REQ as REQ
        from dslabs_tpu.tpu.trace import MessageTemplate

        # Compiled rows are [tag, frm, to, payload...].
        tag, seq = int(rec[0]), int(rec[3])
        master = LocalAddress(self.master_name)
        client = LocalAddress(self.client_name)
        if tag == REQ:
            cmd = self.pairs[seq - 1][0]
            return client, master, PaxosRequest(
                AMOCommand(cmd, client, seq))
        res = self.pairs[seq - 1][1]
        fallback = (PaxosReply(AMOResult(res, seq))
                    if res is not None else None)
        return master, client, MessageTemplate(
            PaxosReply, fallback,
            lambda m, s=seq: m.result.sequence_num == s)

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.paxos import paxos as P
        from dslabs_tpu.tpu.specs_lab4 import (
            CLIENT_MS, ELECTION_MAX, ELECTION_MIN, HEARTBEAT_MS,
            JOIN_T_CLIENT as T_CLIENT, JOIN_T_ELECTION as T_ELECTION,
            JOIN_T_HEARTBEAT as T_HEARTBEAT)

        tag, p0 = int(rec[0]), int(rec[3])
        if tag == T_ELECTION:
            return (LocalAddress(self.master_name), P.ElectionTimer(),
                    ELECTION_MIN, ELECTION_MAX)
        if tag == T_HEARTBEAT:
            return (LocalAddress(self.master_name),
                    P.HeartbeatTimer(self.master_ballot),
                    HEARTBEAT_MS, HEARTBEAT_MS)
        if tag == T_CLIENT:
            return (LocalAddress(self.client_name), P.ClientTimer(p0),
                    CLIENT_MS, CLIENT_MS)
        raise NoTensorTwin(f"unknown join timer tag {tag}")

    # ---------------------------------------------------------------- masks

    def msg_mask_fn(self):
        def fn(msg, marr):
            import jax.numpy as jnp

            # Compiled rows carry frm/to lanes: flat = frm * 2 + to.
            k = msg[1] * 2 + msg[2]
            return jnp.sum(jnp.where(jnp.arange(4) == k, marr, False))
        return fn

    # ----------------------------------------------------------- predicates

    def predicate(self, tkey):
        kind = tkey[0]
        w = self.w

        def k(s):
            return s["nodes"][3]                       # K lane

        def const_true(s):
            return k(s) >= 1
        const_true.value_level = True

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME"):
            return const_true
        if kind == "CLIENTS_DONE":
            return lambda s: k(s) == w + 1
        if kind == "CLIENT_DONE":
            if str(tkey[1].root_address()) != self.client_name:
                return None
            return lambda s: k(s) == w + 1
        if kind == "CLIENT_HAS_RESULTS":
            if str(tkey[1].root_address()) != self.client_name:
                return None
            num = tkey[2]
            return lambda s: k(s) >= num + 1
        if kind == "NONE_DECIDED":
            return lambda s: k(s) == 1
        return None


class ShardStoreBinding(TwinBinding):
    """Main-phase binding: G one-server groups + one shard master + one
    ShardStoreClient worker over a KV workload (the ShardStorePart1Test
    test10/test11 shapes; tpu/specs_lab4.py
    make_shardstore_protocol)."""

    def __init__(self, state, master_addr, kv_addrs, ctl_addrs):
        from dslabs_tpu.labs.shardedstore.shardmaster import ShardConfig
        from dslabs_tpu.labs.shardedstore.shardstore import (
            ShardStoreServer, key_to_shard)
        from dslabs_tpu.labs.shardedstore.txkvstore import Transaction

        self.master_name = str(master_addr)
        kv_addrs = sorted(kv_addrs, key=str)
        self.client_names = [str(a) for a in kv_addrs]
        self.NC = len(kv_addrs)
        self.ctl_names = [str(a) for a in ctl_addrs]
        master = state.servers[master_addr]

        # Store groups: exactly one server per group, contiguous ids.
        by_group: Dict[int, object] = {}
        for a, s in state.servers.items():
            if isinstance(s, ShardStoreServer):
                if s.group_id in by_group:
                    raise NoTensorTwin(
                        "shardstore twin models ONE server per group "
                        f"(group {s.group_id} has several) — use the "
                        "multi-server twin shapes")
                by_group[s.group_id] = (a, s)
        self.G = len(by_group)
        if sorted(by_group) != list(range(1, self.G + 1)):
            raise NoTensorTwin(
                f"group ids must be 1..G, got {sorted(by_group)}")
        if self.G > 2:
            raise NoTensorTwin(
                "shardstore twin models at most 2 groups "
                "(3+ need multi-hop handoff modelling)")
        self.server_names = [str(by_group[g][0])
                             for g in range(1, self.G + 1)]
        self.server_addrs = [by_group[g][0]
                             for g in range(1, self.G + 1)]
        # Per-group paxos sub-node self-election ballot (constant) for
        # HeartbeatTimer decode.
        self.ballots = [by_group[g][1].paxos.ballot
                        for g in range(1, self.G + 1)]

        self.addr_index = {self.master_name: 0}
        for g, n in enumerate(self.server_names, start=1):
            self.addr_index[n] = g
        for c, n in enumerate(self.client_names):
            self.addr_index[n] = self.G + 1 + c
        # The controller rides as the last twin node when its join-phase
        # debris is deliverable (model_ctl); harmless padding otherwise.
        if len(self.ctl_names) == 1:
            self.addr_index[self.ctl_names[0]] = self.G + 1 + self.NC
        self.master_ballot = master.ballot
        self.ctl_pairs = ([_workload_pairs(state.client_workers()[
            ctl_addrs[0]], ctl_addrs[0])] if len(ctl_addrs) == 1 else [])
        # Settings-dependent modelling flags; bound in check_settings
        # (called before build_protocol, backend._run_tensor).
        self._model_mh = False
        self._model_ctl = False

        # The decided config walk, read from the staged master's app.
        app = master.app.application if master.app is not None else None
        configs = getattr(app, "configs", None)
        if not configs or len(configs) != self.G:
            raise NoTensorTwin(
                f"master has {len(configs or [])} configs, twin expects "
                f"one per group ({self.G})")
        if not all(isinstance(c, ShardConfig) for c in configs):
            raise NoTensorTwin("master configs are not ShardConfigs")
        self.configs: List[ShardConfig] = list(configs)
        self.num_shards = by_group[1][1].num_shards
        if self.G == 2:
            # The twin's handoff model assumes cfg0 assigns every shard
            # to group 1 (successive Joins).
            for s in range(1, self.num_shards + 1):
                if self.configs[0].group_of(s) != 1:
                    raise NoTensorTwin(
                        "twin assumes the first config assigns all "
                        f"shards to group 1 (shard {s} differs)")

        # Workloads -> per-client, per-command owning group under the
        # final config.
        final = self.configs[-1]
        workers = state.client_workers()
        self.pairs = []                     # per client: [(cmd, res)]
        self.groups_of: List[List[int]] = []
        for addr in kv_addrs:
            pairs = _workload_pairs(workers[addr], addr)
            gs = []
            for cmd, _ in pairs:
                if isinstance(cmd, Transaction):
                    # A SINGLE-group transaction executes like any app
                    # command (shards <= mine -> app.execute, no 2PC:
                    # shardstore.py _execute_client_command) — the twin
                    # is command-content agnostic, so it binds here.
                    # Cross-group transactions route to TxBinding.
                    tgs = {final.group_of(key_to_shard(k,
                                                       self.num_shards))
                           for k in cmd.key_set()}
                    if len(tgs) != 1:
                        raise NoTensorTwin(
                            f"cross-group transaction {cmd!r} — the tx "
                            "twin covers those shapes")
                    gs.append(tgs.pop())
                    continue
                key = getattr(cmd, "key", None)
                if key is None:
                    raise NoTensorTwin(f"command {cmd!r} has no key")
                g = final.group_of(key_to_shard(key, self.num_shards))
                if g is None or not 1 <= g <= self.G:
                    raise NoTensorTwin(
                        f"key {key!r} maps to group {g} outside "
                        f"1..{self.G}")
                gs.append(g)
            self.pairs.append(pairs)
            self.groups_of.append(gs)
        self.Ws = [len(p) for p in self.pairs]
        self.key = ("shardstore", self.master_name,
                    tuple(self.client_names), tuple(self.server_names),
                    tuple(tuple(repr(c) for c, _ in p)
                          for p in self.pairs),
                    tuple(tuple(g) for g in self.groups_of))
        # Client lane offsets (protocol layout: master 1+NC+G, server
        # blocks 6+2NC each, then [k, cfg, cq] per client).
        self._cli0 = (2 + self.NC + self.G) + (6 + 2 * self.NC) * self.G

    def initial_caps(self):
        return 48, 6

    # ------------------------------------------------------------- settings

    def check_settings(self, settings) -> None:
        """Bind the settings-dependent modelling flags: live master
        timers -> model the heard lane + election/heartbeat; an active
        controller -> model its node + join debris (test13's random
        search narrows nothing).  Suppressed events stay unmodelled —
        the runtime masks would gate them anyway, but the narrow twin
        keeps the event grids small."""
        from dslabs_tpu.core.address import LocalAddress

        self._model_mh = settings.should_deliver_timer(
            LocalAddress(self.master_name))
        live = _ctl_live(settings, self.ctl_names, self.master_name)
        if live and len(self.ctl_names) != 1:
            raise NoTensorTwin(
                f"controllers {live} are active but the twin models at "
                "most one controller node")
        self._model_ctl = bool(live)

    # ----------------------------------------------------------------- root

    def derive_root(self, search, state):
        """The twin's initial state IS the canonical joined root — so
        instead of provenance replay, VALIDATE that the staged object
        state matches it field by field (any deviation is loud)."""
        prov = getattr(state, "_tensor_provenance", None)
        if prov is not None and prov.key == self.key:
            from dslabs_tpu.tpu import backend as _b

            return _b.derive_root(self, search, state)
        if getattr(state, "_staged_ops", None):
            raise NoTensorTwin(
                "staged network ops on the joined root are not part of "
                "the canonical lab4 shape")

        _validate_joined_root(state, self.master_name,
                              self.server_names, self.client_names)

        def req(cond, what):
            if not cond:
                raise NoTensorTwin(
                    f"staged state is not the canonical joined root: "
                    f"{what}")

        by_name = {str(a): s for a, s in state.servers.items()}
        master = by_name[self.master_name]
        if self._model_mh:
            req(master.heard_from_leader,
                "master heard_from_leader is False (twin init assumes "
                "the clean join path's final self-P2a)")
            kinds = [type(t.timer).__name__
                     for t in state.timers(
                         self._addr(self.master_name))]
            req(kinds == ["ElectionTimer", "HeartbeatTimer"],
                f"master timer queue {kinds} != [Election, Heartbeat]")
        if self._model_ctl:
            from dslabs_tpu.labs.paxos.paxos import (ClientTimer,
                                                     PaxosReply,
                                                     PaxosRequest)

            name = self.ctl_names[0]
            workers = {str(a): w
                       for a, w in state.client_workers().items()}
            ctl_client = workers[name].client
            G = self.G
            req(ctl_client.pending is None and ctl_client.seq_num == G,
                f"controller {name} join workload not drained")
            reqs, reps = set(), set()
            for m in state.network():
                frm, to = str(m.frm.root_address()), str(
                    m.to.root_address())
                if frm == name and to == self.master_name:
                    req(isinstance(m.message, PaxosRequest),
                        f"unexpected controller message {m.message!r}")
                    reqs.add(m.message.command.sequence_num)
                elif frm == self.master_name and to == name:
                    req(isinstance(m.message, PaxosReply),
                        f"unexpected controller reply {m.message!r}")
                    reps.add(m.message.result.sequence_num)
            want = set(range(1, G + 1))
            req(reqs == want and reps == want,
                f"join debris REQ {sorted(reqs)} / REP {sorted(reps)} "
                f"!= the clean path's {sorted(want)}")
            cts = [t.timer for t in state.timers(self._addr(name))]
            req(all(isinstance(t, ClientTimer) for t in cts)
                and [t.sequence_num for t in cts] == list(range(1, G + 1)),
                f"controller timer queue {cts} != ClientTimer(1..{G})")
        return None, []

    # ------------------------------------------------------------- protocol

    def build_protocol(self, net_cap, timer_cap):
        from dslabs_tpu.tpu.specs_lab4 import \
            make_shardstore_protocol

        p = make_shardstore_protocol(
            self.groups_of, net_cap=max(net_cap, 48),
            timer_cap=max(timer_cap, 6),
            model_master_timers=self._model_mh,
            model_ctl=self._model_ctl)
        return dataclasses.replace(
            p, decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    # ------------------------------------------------------------ decoders

    def _addr(self, name):
        from dslabs_tpu.core.address import LocalAddress

        return LocalAddress(name)

    def _decode_message(self, rec):
        from dslabs_tpu.labs.clientserver.amo import AMOCommand, AMOResult
        from dslabs_tpu.labs.paxos.paxos import PaxosReply, PaxosRequest
        from dslabs_tpu.labs.shardedstore.shardmaster import (Query,
                                                              ShardConfig)
        from dslabs_tpu.labs.shardedstore.shardstore import (
            ShardMove, ShardMoveAck, ShardStoreReply, ShardStoreRequest,
            WrongGroup)
        from dslabs_tpu.tpu.specs_lab4 import (JREP, JREQ, QREP, QRY,
                                               SM, SMACK, SSREP, SSREQ,
                                               WG)
        from dslabs_tpu.tpu.trace import MessageTemplate

        # Compiled rows are [tag, frm, to, payload...]; the payload
        # field orders below mirror the spec's MessageType tuples.
        r = [int(x) for x in rec]
        tag, a, b, c = r[0], r[3], r[4], (r[5] if len(r) > 5 else 0)
        master = self._addr(self.master_name)
        NC = self.NC
        final_num = self.configs[-1].config_num
        if tag == QRY:
            frm = (self._addr(self.client_names[a]) if a < NC
                   else self._addr(self.server_names[a - NC]))
            return frm, master, PaxosRequest(
                AMOCommand(Query(c), frm, b))
        if tag == QREP:
            to = (self._addr(self.client_names[a]) if a < NC
                  else self._addr(self.server_names[a - NC]))
            return master, to, MessageTemplate(
                PaxosReply, None,
                lambda m, s=b: (m.result.sequence_num == s
                                and isinstance(m.result.result,
                                               ShardConfig)))
        if tag == SSREQ:
            client = self._addr(self.client_names[a])
            g = self.groups_of[a][b - 1]
            cmd = self.pairs[a][b - 1][0]
            return client, self._addr(self.server_names[g - 1]), \
                ShardStoreRequest(AMOCommand(cmd, client, b))
        if tag == SSREP:
            client = self._addr(self.client_names[a])
            g = self.groups_of[a][b - 1]
            res = self.pairs[a][b - 1][1]
            fallback = (ShardStoreReply(AMOResult(res, b))
                        if res is not None else None)
            return self._addr(self.server_names[g - 1]), client, \
                MessageTemplate(
                    ShardStoreReply, fallback,
                    lambda m, s=b: m.result.sequence_num == s)
        if tag == WG:
            client = self._addr(self.client_names[a])
            g = self.groups_of[a][b - 1]
            return (self._addr(self.server_names[g - 1]), client,
                    WrongGroup(b))
        if tag == SM:
            return (self._addr(self.server_names[0]),
                    self._addr(self.server_names[1]),
                    MessageTemplate(
                        ShardMove, None,
                        lambda m: (m.config_num == final_num
                                   and m.from_group == 1)))
        if tag == SMACK:
            return (self._addr(self.server_names[1]),
                    self._addr(self.server_names[0]),
                    MessageTemplate(
                        ShardMoveAck, None,
                        lambda m: m.config_num == final_num))
        if tag == JREQ:
            ctl = self._addr(self.ctl_names[0])
            cmd = self.ctl_pairs[0][a - 1][0]
            return ctl, master, PaxosRequest(AMOCommand(cmd, ctl, a))
        if tag == JREP:
            ctl = self._addr(self.ctl_names[0])
            res = self.ctl_pairs[0][a - 1][1]
            fallback = (PaxosReply(AMOResult(res, a))
                        if res is not None else None)
            return master, ctl, MessageTemplate(
                PaxosReply, fallback,
                lambda m, s=a: m.result.sequence_num == s)
        raise NoTensorTwin(f"unknown shardstore message tag {tag}")

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.core.address import SubAddress
        from dslabs_tpu.labs.paxos import paxos as P
        from dslabs_tpu.labs.shardedstore.shardstore import (ClientTimer,
                                                             QueryTimer)
        from dslabs_tpu.tpu.specs_lab4 import (CLIENT_MS,
                                                         ELECTION_MAX,
                                                         ELECTION_MIN,
                                                         HEARTBEAT_MS,
                                                         QUERY_MS,
                                                         T_CLIENT,
                                                         T_ELECTION,
                                                         T_HEARTBEAT,
                                                         T_QUERY)

        tag, p0 = int(rec[0]), int(rec[3])
        node_idx = int(node_idx)
        if node_idx == 0:
            # Master-level paxos timers (model_master_timers).
            if tag == T_ELECTION:
                return (self._addr(self.master_name), P.ElectionTimer(),
                        ELECTION_MIN, ELECTION_MAX)
            return (self._addr(self.master_name),
                    P.HeartbeatTimer(self.master_ballot),
                    HEARTBEAT_MS, HEARTBEAT_MS)
        if node_idx == self.G + 1 + self.NC:
            # The controller's stale join-phase ClientTimer (model_ctl).
            return (self._addr(self.ctl_names[0]), P.ClientTimer(p0),
                    CLIENT_MS, CLIENT_MS)
        if tag == T_CLIENT:
            c = node_idx - self.G - 1
            return (self._addr(self.client_names[c]), ClientTimer(p0),
                    CLIENT_MS, CLIENT_MS)
        g = node_idx                           # 1..G
        name = self.server_names[g - 1]
        if tag == T_QUERY:
            return (self._addr(name), QueryTimer(), QUERY_MS, QUERY_MS)
        sub = SubAddress(self._addr(name), PAXOS_ID)
        if tag == T_ELECTION:
            return (sub, P.ElectionTimer(), ELECTION_MIN, ELECTION_MAX)
        if tag == T_HEARTBEAT:
            return (sub, P.HeartbeatTimer(self.ballots[g - 1]),
                    HEARTBEAT_MS, HEARTBEAT_MS)
        raise NoTensorTwin(f"unknown shardstore timer tag {tag}")

    # ---------------------------------------------------------------- masks

    def msg_mask_fn(self):
        nn = len(self.addr_index)

        def fn(msg, marr):
            import jax.numpy as jnp

            # Compiled rows carry frm/to lanes directly, and the
            # spec's node order matches addr_index (master 0, servers
            # 1..G, clients G+1.., controller last).
            k = msg[1] * nn + msg[2]
            return jnp.sum(jnp.where(jnp.arange(nn * nn) == k, marr,
                                     False))
        return fn

    # ----------------------------------------------------------- predicates

    def predicate(self, tkey):
        import jax.numpy as jnp

        kind = tkey[0]
        Ws, cli0 = self.Ws, self._cli0

        def k(s, c):
            return s["nodes"][cli0 + 3 * c]

        def const_true(s):
            return k(s, 0) >= 1
        const_true.value_level = True

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME"):
            return const_true
        if kind == "CLIENTS_DONE":
            def fn(s):
                done = jnp.asarray(True)
                for c in range(self.NC):
                    done = done & (k(s, c) == Ws[c] + 1)
                return done
            return fn
        if kind in ("CLIENT_DONE", "CLIENT_HAS_RESULTS"):
            name = str(tkey[1].root_address())
            if name not in self.client_names:
                return None
            c = self.client_names.index(name)
            if kind == "CLIENT_DONE":
                return lambda s: k(s, c) == Ws[c] + 1
            num = tkey[2]
            return lambda s: k(s, c) >= num + 1
        if kind == "NONE_DECIDED":
            def fn(s):
                nd = jnp.asarray(True)
                for c in range(self.NC):
                    nd = nd & (k(s, c) == 1)
                return nd
            return fn
        return None


class ShardStoreTxBinding(TwinBinding):
    """Cross-group-transaction binding (ShardStorePart2Test.test09 /
    our test09_single_client_multi_group_tx_search): two one-server
    groups, one client whose every command is a Transaction spanning
    BOTH groups with its minimum shard owned by group 1 (the static
    coordinator) — the shardstore_tx twin's exact scope.  Node order
    mirrors the twin: master 0, servers 1..2, client 3."""

    def __init__(self, state, master_addr, kv_addr, ctl_addrs):
        from dslabs_tpu.labs.shardedstore.shardmaster import ShardConfig
        from dslabs_tpu.labs.shardedstore.shardstore import (
            ShardStoreServer, key_to_shard)
        from dslabs_tpu.labs.shardedstore.txkvstore import Transaction

        self.master_name = str(master_addr)
        self.client_name = str(kv_addr)
        self.ctl_names = [str(a) for a in ctl_addrs]
        master = state.servers[master_addr]

        by_group = {}
        for a, s in state.servers.items():
            if isinstance(s, ShardStoreServer):
                if s.group_id in by_group:
                    raise NoTensorTwin(
                        "tx twin models ONE server per group")
                by_group[s.group_id] = (a, s)
        if sorted(by_group) != [1, 2]:
            raise NoTensorTwin(
                f"tx twin models exactly groups 1..2, got "
                f"{sorted(by_group)}")
        self.server_names = [str(by_group[g][0]) for g in (1, 2)]
        self.ballots = [by_group[g][1].paxos.ballot for g in (1, 2)]
        self.master_ballot = master.ballot
        self.num_shards = by_group[1][1].num_shards

        self.addr_index = {self.master_name: 0,
                           self.server_names[0]: 1,
                           self.server_names[1]: 2,
                           self.client_name: 3}

        app = master.app.application if master.app is not None else None
        configs = getattr(app, "configs", None)
        if not configs or len(configs) != 2:
            raise NoTensorTwin(
                f"master has {len(configs or [])} configs, tx twin "
                "expects 2 (Join(1), Join(2))")
        if not all(isinstance(c, ShardConfig) for c in configs):
            raise NoTensorTwin("master configs are not ShardConfigs")
        self.configs = list(configs)
        for s in range(1, self.num_shards + 1):
            if self.configs[0].group_of(s) != 1:
                raise NoTensorTwin(
                    "tx twin assumes cfg0 assigns every shard to g1")

        workers = state.client_workers()
        pairs = _workload_pairs(workers[kv_addr], kv_addr)
        final = self.configs[-1]
        for cmd, _ in pairs:
            if not isinstance(cmd, Transaction):
                raise NoTensorTwin(
                    f"tx twin models all-transaction workloads, got "
                    f"{cmd!r}")
            shards = sorted(key_to_shard(k, self.num_shards)
                            for k in cmd.key_set())
            tgs = {final.group_of(s) for s in shards}
            if tgs != {1, 2}:
                raise NoTensorTwin(
                    f"transaction {cmd!r} spans groups {sorted(tgs)}, "
                    "the tx twin models both-group transactions")
            if final.group_of(min(shards)) != 1:
                raise NoTensorTwin(
                    "tx twin's static coordinator is group 1 (the "
                    "minimum shard's owner)")
        self.pairs = pairs
        self.W = len(pairs)
        self.key = ("shardstore-tx", self.master_name, self.client_name,
                    tuple(self.server_names),
                    tuple(repr(c) for c, _ in pairs))
        # Client workload-index lane (tx twin layout: master 2+G, then
        # per-server blocks 9 + 3W + 7W — the coordinator slot block
        # rides on BOTH servers in the uniform compiled layout, zero
        # on g2).
        self._ck = (2 + 2) + (9 + 10 * self.W) * 2

    def initial_caps(self):
        return 48, 6

    def check_settings(self, settings) -> None:
        from dslabs_tpu.core.address import LocalAddress

        if settings.should_deliver_timer(
                LocalAddress(self.master_name)):
            raise NoTensorTwin(
                "tx twin freezes the master's timers — settings must "
                "deliver_timers(master, False)")
        live = _ctl_live(settings, self.ctl_names, self.master_name)
        if live:
            raise NoTensorTwin(
                f"controllers {live} must be fully suppressed — the "
                "tx twin does not model their debris")

    def derive_root(self, search, state):
        prov = getattr(state, "_tensor_provenance", None)
        if prov is not None and prov.key == self.key:
            from dslabs_tpu.tpu import backend as _b

            return _b.derive_root(self, search, state)
        if getattr(state, "_staged_ops", None):
            raise NoTensorTwin(
                "staged network ops on the joined root are not part of "
                "the canonical lab4 shape")
        _validate_joined_root(state, self.master_name,
                              self.server_names, [self.client_name])
        return None, []

    def build_protocol(self, net_cap, timer_cap):
        from dslabs_tpu.tpu.specs_lab4 import             make_shardstore_tx_protocol

        p = make_shardstore_tx_protocol(
            n_tx=self.W, net_cap=max(net_cap, 48),
            timer_cap=max(timer_cap, 6))
        return dataclasses.replace(
            p, decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    # ------------------------------------------------------------ decoders

    def _addr(self, name):
        from dslabs_tpu.core.address import LocalAddress

        return LocalAddress(name)

    def _amo(self, t):
        from dslabs_tpu.labs.clientserver.amo import AMOCommand

        return AMOCommand(self.pairs[t - 1][0],
                          self._addr(self.client_name), t)

    def _decode_message(self, rec):
        from dslabs_tpu.labs.clientserver.amo import AMOCommand, AMOResult
        from dslabs_tpu.labs.paxos.paxos import PaxosReply, PaxosRequest
        from dslabs_tpu.labs.shardedstore.shardmaster import (Query,
                                                              ShardConfig)
        from dslabs_tpu.labs.shardedstore.shardstore import (
            ShardMove, ShardMoveAck, ShardStoreReply, ShardStoreRequest,
            TxAck, TxDecision, TxPrepare, TxVote, WrongGroup)
        from dslabs_tpu.tpu.specs_lab4 import (QREP, QRY,
                                                            SM, SMACK,
                                                            SSREP,
                                                            SSREQ, TXA,
                                                            TXD, TXP,
                                                            TXV, WG)
        from dslabs_tpu.tpu.trace import MessageTemplate

        r = [int(x) for x in rec]
        # Compiled rows are [tag, frm, to, payload...].
        tag, a, b, c = r[0], r[3], r[4], r[5]
        master = self._addr(self.master_name)
        client = self._addr(self.client_name)
        s1 = self._addr(self.server_names[0])
        s2 = self._addr(self.server_names[1])
        srv_of = {1: s1, 2: s2}
        final_num = self.configs[-1].config_num
        tx_id = lambda t: (client, t)     # noqa: E731
        if tag == QRY:
            frm = client if a == 0 else srv_of[a]
            return frm, master, PaxosRequest(
                AMOCommand(Query(c), frm, b))
        if tag == QREP:
            to = client if a == 0 else srv_of[a]
            return master, to, MessageTemplate(
                PaxosReply, None,
                lambda m, s=b: (m.result.sequence_num == s
                                and isinstance(m.result.result,
                                               ShardConfig)))
        if tag == SSREQ:
            return client, s1, ShardStoreRequest(self._amo(a))
        if tag == SSREP:
            res = self.pairs[a - 1][1]
            fallback = (ShardStoreReply(AMOResult(res, a))
                        if res is not None else None)
            return s1, client, MessageTemplate(
                ShardStoreReply, fallback,
                lambda m, s=a: m.result.sequence_num == s)
        if tag == WG:
            return s1, client, WrongGroup(a)
        if tag == SM:
            return s1, s2, MessageTemplate(
                ShardMove, None,
                lambda m: (m.config_num == final_num
                           and m.from_group == 1))
        if tag == SMACK:
            return s2, s1, MessageTemplate(
                ShardMoveAck, None,
                lambda m: m.config_num == final_num)
        if tag == TXP:
            # The coordinator's prepare: config_num is constantly the
            # final config's (coordination only happens at cfg1), the
            # member tuple is g1's single server.
            return s1, srv_of[c], TxPrepare(
                self._amo(a), b, 1, final_num, (s1,))
        if tag == TXV:
            fg, ok = c // 2, bool(c % 2)
            # Vote VALUES are () in every reachable voting state (the
            # twin's collapse argument, shardstore_tx.py docstring).
            return srv_of[fg], s1, TxVote(tx_id(a), b, fg, ok, ())
        if tag == TXD:
            dst, commit = c // 2, bool(c % 2)
            return s1, srv_of[dst], MessageTemplate(
                TxDecision, None,
                lambda m, t=a, rnd=b, cm=commit: (
                    m.tx_id == tx_id(t) and m.round == rnd
                    and m.commit == cm))
        if tag == TXA:
            return srv_of[c], s1, TxAck(tx_id(a), b, c)
        raise NoTensorTwin(f"unknown tx twin message tag {tag}")

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.core.address import SubAddress
        from dslabs_tpu.labs.paxos import paxos as P
        from dslabs_tpu.labs.shardedstore.shardstore import (ClientTimer,
                                                             QueryTimer)
        from dslabs_tpu.tpu.specs_lab4 import (CLIENT_MS,
                                                            ELECTION_MAX,
                                                            ELECTION_MIN,
                                                            HEARTBEAT_MS,
                                                            QUERY_MS,
                                                            T_CLIENT,
                                                            T_ELECTION,
                                                            T_HEARTBEAT,
                                                            T_QUERY)

        tag, p0 = int(rec[0]), int(rec[3])
        node_idx = int(node_idx)
        if tag == T_CLIENT:
            return (self._addr(self.client_name), ClientTimer(p0),
                    CLIENT_MS, CLIENT_MS)
        name = self.server_names[node_idx - 1]
        if tag == T_QUERY:
            return (self._addr(name), QueryTimer(), QUERY_MS, QUERY_MS)
        sub = SubAddress(self._addr(name), "paxos")
        if tag == T_ELECTION:
            return (sub, P.ElectionTimer(), ELECTION_MIN, ELECTION_MAX)
        if tag == T_HEARTBEAT:
            return (sub, P.HeartbeatTimer(self.ballots[node_idx - 1]),
                    HEARTBEAT_MS, HEARTBEAT_MS)
        raise NoTensorTwin(f"unknown tx twin timer tag {tag}")

    # ---------------------------------------------------------------- masks

    def msg_mask_fn(self):
        nn = len(self.addr_index)

        def fn(msg, marr):
            import jax.numpy as jnp

            # Compiled rows carry real frm/to lanes at msg[1]/msg[2]
            # (node order matches addr_index: master, s1, s2, client).
            k = msg[1] * nn + msg[2]
            return jnp.sum(jnp.where(jnp.arange(nn * nn) == k, marr,
                                     False))
        return fn

    # ----------------------------------------------------------- predicates

    def predicate(self, tkey):
        kind = tkey[0]
        W, ck = self.W, self._ck

        def k(s):
            return s["nodes"][ck]

        def const_true(s):
            return k(s) >= 1
        const_true.value_level = True

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME", "MULTI_GETS_MATCH"):
            return const_true
        if kind == "CLIENTS_DONE":
            return lambda s: k(s) == W + 1
        if kind == "CLIENT_DONE":
            if str(tkey[1].root_address()) != self.client_name:
                return None
            return lambda s: k(s) == W + 1
        if kind == "CLIENT_HAS_RESULTS":
            if str(tkey[1].root_address()) != self.client_name:
                return None
            num = tkey[2]
            return lambda s: k(s) >= num + 1
        if kind == "NONE_DECIDED":
            return lambda s: k(s) == 1
        return None


@register_adapter
def match_shardstore(state):
    from dslabs_tpu.labs.paxos.paxos import PaxosClient, PaxosServer
    from dslabs_tpu.labs.shardedstore.shardmaster import ShardMasterCommand
    from dslabs_tpu.labs.shardedstore.shardstore import (ShardStoreClient,
                                                         ShardStoreServer)

    servers = state.servers
    if not servers:
        return None
    stores = [a for a, s in servers.items()
              if isinstance(s, ShardStoreServer)]
    masters = [a for a, s in servers.items()
               if isinstance(s, PaxosServer)]
    if not stores or not masters:
        return None
    workers = state.client_workers()
    if not workers:
        return None
    kv = [a for a, w in workers.items()
          if isinstance(w.client, ShardStoreClient)]
    ctl = [a for a, w in workers.items()
          if isinstance(w.client, PaxosClient)]
    if len(kv) + len(ctl) != len(workers):
        return None
    if not kv:
        # Join phase: one controller driving ShardMaster commands.
        if len(ctl) != 1:
            return None
        wl = workers[ctl[0]].workload
        if wl.infinite():
            return None
        cmds = wl._commands
        if not cmds or not all(isinstance(c, ShardMasterCommand)
                               for c in cmds):
            return None
        return JoinBinding(state, _single(masters, "shard master"),
                           ctl[0], stores)
    # Main phase: controllers must be finished (their workload
    # drained).  Workloads containing a CROSS-group transaction bind to
    # the 2PC twin; everything else (plain commands and single-group
    # transactions, which execute without 2PC) binds to the Part-1 twin.
    from dslabs_tpu.labs.shardedstore.shardmaster import ShardConfig
    from dslabs_tpu.labs.shardedstore.shardstore import key_to_shard
    from dslabs_tpu.labs.shardedstore.txkvstore import Transaction

    master_addr = _single(masters, "shard master")
    master = servers[master_addr]
    app = master.app.application if master.app is not None else None
    configs = getattr(app, "configs", None)
    cross = False
    if configs and all(isinstance(c, ShardConfig) for c in configs):
        final = configs[-1]
        ns = next(s for s in servers.values()
                  if isinstance(s, ShardStoreServer)).num_shards
        for a in kv:
            if workers[a].workload.infinite():
                continue
            # Materialize through the same path the bindings use, so
            # string-template workloads whose PARSER yields
            # Transactions route correctly too.
            for cmd, _ in _workload_pairs(workers[a], a):
                if isinstance(cmd, Transaction) and len(
                        {final.group_of(key_to_shard(k, ns))
                         for k in cmd.key_set()}) > 1:
                    cross = True
    if cross:
        return ShardStoreTxBinding(state, master_addr,
                                   _single(kv, "tx-workload client"),
                                   ctl)
    return ShardStoreBinding(state, master_addr, kv, ctl)
