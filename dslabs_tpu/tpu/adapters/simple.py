"""Lab 0 (ping-pong) and lab 1 (exactly-once client/server) twin
adapters for the harness search backend (tpu/backend.py).

Both twins collapse application values to per-client sequence progress
(tpu/protocols/pingpong.py, clientserver.py docstrings); the adapters
rebuild exact object messages from the binding's ACTUAL workloads, and
resolve the one value the twins do not model — the server reply's
application result — from the replayed object state's network via
MessageTemplate (tpu/trace.py), the same value-collapse discipline as
the paxos adapter (tpu/adapters/paxos.py docstring)."""

from __future__ import annotations

import copy
import dataclasses

from typing import Dict, Optional

from dslabs_tpu.tpu.adapters.paxos import _num_suffix, _workload_pairs
from dslabs_tpu.tpu.backend import (NoTensorTwin, TwinBinding,
                                    register_adapter)

__all__ = ["PingPongBinding", "ClientServerBinding"]


class PingPongBinding(TwinBinding):
    """One PingServer + one ClientWorker(PingClient) walking a finite
    echo workload; twin node indices: server 0, client 1."""

    def __init__(self, state):
        workers = state.client_workers()
        self.server_name = str(next(iter(state.servers)))
        self.client_name = str(next(iter(workers)))
        self.addr_index = {self.server_name: 0, self.client_name: 1}
        (addr, worker), = workers.items()
        pairs = _workload_pairs(worker, addr)
        self.cmds = [c for c, _ in pairs]
        for c, r in pairs:
            if r is not None and r.value != c.value:
                raise NoTensorTwin(
                    "pingpong twin models the echo server; expected "
                    f"result {r!r} != command {c!r}")
        self.w = len(pairs)
        self.key = ("pingpong", self.server_name, self.client_name,
                    tuple(repr(c) for c in self.cmds))

    def initial_caps(self):
        return 8, 4

    def build_protocol(self, net_cap, timer_cap):
        from dslabs_tpu.tpu.protocols.pingpong import \
            make_pingpong_protocol

        p = make_pingpong_protocol(self.w)
        return dataclasses.replace(
            p, net_cap=max(net_cap // 4, p.net_cap),
            timer_cap=max(timer_cap // 2, p.timer_cap),
            decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    def _decode_message(self, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.pingpong.pingpong import (PingRequest,
                                                       PongReply, Pong)
        from dslabs_tpu.tpu.protocols.pingpong import REQ

        tag, i = int(rec[0]), int(rec[1])
        server = LocalAddress(self.server_name)
        client = LocalAddress(self.client_name)
        cmd = self.cmds[i - 1]
        if tag == REQ:
            return client, server, PingRequest(cmd)
        return server, client, PongReply(Pong(cmd.value))

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.pingpong.pingpong import PingTimer
        from dslabs_tpu.tpu.protocols.pingpong import PING_MS

        i = int(rec[3])
        return (LocalAddress(self.client_name), PingTimer(self.cmds[i - 1]),
                PING_MS, PING_MS)

    def msg_mask_fn(self):
        # Record layout [tag, i]: REQ rides client(1) -> server(0),
        # REPLY the reverse — no frm/to lanes to read.
        from dslabs_tpu.tpu.protocols.pingpong import REQ

        def fn(msg, marr):
            import jax.numpy as jnp

            k = jnp.where(msg[0] == REQ, 1 * 2 + 0, 0 * 2 + 1)
            return jnp.sum(jnp.where(jnp.arange(4) == k, marr, False))
        return fn

    def predicate(self, tkey):
        kind = tkey[0]
        w = self.w

        def k(s):
            return s["nodes"][0]

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME"):
            fn = lambda s: k(s) >= 0    # noqa: E731
            fn.value_level = True       # object-side re-check on exhaust
            return fn
        if kind in ("CLIENTS_DONE", "CLIENT_DONE"):
            return lambda s: k(s) == w + 1
        if kind == "NONE_DECIDED":
            return lambda s: k(s) == 1
        if kind == "CLIENT_HAS_RESULTS":
            return lambda s: k(s) >= tkey[2] + 1
        return None


class _StreamPairs:
    """Command lookup for INFINITE workloads under the counter-mode
    deterministic streams (testing/workload.py stream_rng): the pair at
    1-based index i is a pure function of (client address, i-1), so
    decode seeks the workload copy directly — no history replay, no
    global-rng irreproducibility (round-4 verdict item 8; the previous
    shape was a loud _NoDecodePairs refusal)."""

    def __init__(self, workload, addr):
        import copy as _copy

        self._wl = _copy.deepcopy(workload)
        self._addr = addr
        self._cache: Dict[int, tuple] = {}

    def __getitem__(self, i):
        from dslabs_tpu.testing.workload import derandomized

        if not derandomized():
            raise NoTensorTwin(
                "random infinite-workload commands are not "
                "reconstructible without the tensor strategy's "
                "derandomized streams")
        if i not in self._cache:
            self._wl._i = i
            self._cache[i] = self._wl._next_pair(self._addr)
        return self._cache[i]


class ClientServerBinding(TwinBinding):
    """One SimpleServer + NC ClientWorker(SimpleClient)s with finite OR
    infinite KV workloads; twin node indices: server 0, client c ->
    1 + c.  Infinite workloads bind with an unreachable done bound (the
    per-client seq lanes are unbounded int32 either way) and lazy
    command decode."""

    def __init__(self, state):
        workers = state.client_workers()
        clients = sorted(workers,
                         key=lambda a: _num_suffix(str(a), "client") or 0)
        self.server_name = str(next(iter(state.servers)))
        self.client_names = [str(a) for a in clients]
        self.nc = len(clients)
        self.addr_index = {self.server_name: 0}
        self.addr_index.update(
            {c: 1 + j for j, c in enumerate(self.client_names)})
        infinite = [workers[a].workload.infinite() for a in clients]
        if all(infinite):
            self.w = 1 << 20        # done (k == w + 1) is unreachable
            self.pairs = [_StreamPairs(workers[a].workload, a)
                          for a in clients]
            # Counter-mode streams are a pure function of (address,
            # index) AND the workload template, so the key carries the
            # type + template signature: same-type workloads with
            # different command templates must NOT be interchangeable
            # across staged phases (the command reconstruction would
            # silently decode the wrong commands), while identical
            # templates are (round-4: a uuid nonce made every staged
            # reuse a refusal).
            def sig(wl):
                return (type(wl).__name__,
                        tuple(wl._command_strings or ())
                        if wl._commands is None
                        else tuple(repr(c) for c in wl._commands),
                        tuple(wl._result_strings or ()))

            self.key = ("clientserver", self.server_name,
                        tuple(self.client_names), "infinite",
                        tuple(sig(workers[a].workload)
                              for a in clients))
        elif any(infinite):
            raise NoTensorTwin("mixed finite/infinite workloads")
        else:
            pairs = [_workload_pairs(workers[a], a) for a in clients]
            sizes = {len(p) for p in pairs}
            if len(sizes) != 1:
                raise NoTensorTwin(
                    f"per-client workload sizes differ ({sizes})")
            self.w = sizes.pop()
            self.pairs = pairs
            self.key = ("clientserver", self.server_name,
                        tuple(self.client_names),
                        tuple(repr(c) for p in pairs for c, _ in p))

    def initial_caps(self):
        return 16, 4

    def build_protocol(self, net_cap, timer_cap):
        from dslabs_tpu.tpu.protocols.clientserver import \
            make_clientserver_protocol

        p = make_clientserver_protocol(n_clients=self.nc, w=self.w,
                                       net_cap=net_cap,
                                       timer_cap=timer_cap)
        return dataclasses.replace(
            p, decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    def _amo(self, c, s):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.amo import AMOCommand

        return AMOCommand(self.pairs[c][s - 1][0],
                          LocalAddress(self.client_names[c]), s)

    def _decode_message(self, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.amo import AMOResult
        from dslabs_tpu.labs.clientserver.clientserver import (Reply,
                                                               Request)
        from dslabs_tpu.tpu.protocols.clientserver import REQ
        from dslabs_tpu.tpu.trace import MessageTemplate

        tag, c, s = int(rec[0]), int(rec[1]), int(rec[2])
        server = LocalAddress(self.server_name)
        client = LocalAddress(self.client_names[c])
        if tag == REQ:
            return client, server, Request(self._amo(c, s))
        fallback = Reply(AMOResult(self.pairs[c][s - 1][1], s))
        return server, client, MessageTemplate(
            Reply, fallback, lambda m, s=s: m.result.sequence_num == s)

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.clientserver import ClientTimer
        from dslabs_tpu.tpu.protocols.clientserver import CLIENT_MS

        c, s = int(node_idx) - 1, int(rec[3])
        return (LocalAddress(self.client_names[c]),
                ClientTimer(self._amo(c, s)), CLIENT_MS, CLIENT_MS)

    def msg_mask_fn(self):
        # Record layout [tag, c, s]: REQ rides client(1+c) -> server(0),
        # REPLY the reverse — frm/to derive from (tag, c).
        from dslabs_tpu.tpu.protocols.clientserver import REQ

        nn = 1 + self.nc

        def fn(msg, marr, nn=nn):
            import jax.numpy as jnp

            c = msg[1].clip(0, nn - 2)
            k = jnp.where(msg[0] == REQ, (1 + c) * nn + 0, 0 * nn + 1 + c)
            return jnp.sum(jnp.where(jnp.arange(nn * nn) == k, marr,
                                     False))
        return fn

    def predicate(self, tkey):
        import jax.numpy as jnp

        kind = tkey[0]
        nc, w = self.nc, self.w

        def k(s, c):
            return s["nodes"][nc + c]

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME"):
            fn = lambda s: k(s, 0) >= 0  # noqa: E731
            fn.value_level = True        # object-side re-check on exhaust
            return fn
        if kind == "CLIENTS_DONE":
            def fn(s):
                done = jnp.asarray(True)
                for c in range(nc):
                    done = done & (k(s, c) == w + 1)
                return done
            return fn
        if kind == "NONE_DECIDED":
            def fn(s):
                nd = jnp.asarray(True)
                for c in range(nc):
                    nd = nd & (k(s, c) == 1)
                return nd
            return fn
        if kind == "CLIENT_DONE":
            c = self.client_names.index(str(tkey[1].root_address()))
            return lambda s: k(s, c) == w + 1
        if kind == "CLIENT_HAS_RESULTS":
            c = self.client_names.index(str(tkey[1].root_address()))
            return lambda s: k(s, c) >= tkey[2] + 1
        return None


@register_adapter
def match_pingpong(state):
    from dslabs_tpu.labs.pingpong.pingpong import PingClient, PingServer

    servers = state.servers
    workers = state.client_workers()
    if len(servers) != 1 or len(workers) != 1:
        return None
    if not all(isinstance(s, PingServer) for s in servers.values()):
        return None
    if not all(isinstance(wk.client, PingClient)
               for wk in workers.values()):
        return None
    return PingPongBinding(state)


@register_adapter
def match_clientserver(state):
    from dslabs_tpu.labs.clientserver.clientserver import (SimpleClient,
                                                           SimpleServer)

    servers = state.servers
    workers = state.client_workers()
    if len(servers) != 1 or not workers:
        return None
    if not all(isinstance(s, SimpleServer) for s in servers.values()):
        return None
    if not all(isinstance(wk.client, SimpleClient)
               for wk in workers.values()):
        return None
    return ClientServerBinding(state)


class PrimaryBackupBinding(TwinBinding):
    """Lab 2: ViewServer + NS PBServers + NC ClientWorker(PBClient)s with
    finite KV workloads; twin node indices: viewserver 0, server{s} -> s,
    client c -> NS + 1 + c (tpu/protocols/primarybackup.py lane table).
    The StateTransfer's full application payload — the one field the twin
    collapses to per-client AMO seqs — resolves from the replayed object
    state's network, discriminated by (view_num, per-client last-executed
    seqs), which is exact within the twin's collapse."""

    def __init__(self, state):
        workers = state.client_workers()
        servers = [a for a in state.servers
                   if _num_suffix(str(a), "server") is not None]
        vs = [a for a in state.servers if str(a) not in
              {str(s) for s in servers}]
        if len(vs) != 1:
            raise NoTensorTwin("expected exactly one ViewServer")
        self.vs_name = str(vs[0])
        servers.sort(key=lambda a: _num_suffix(str(a), "server"))
        clients = sorted(workers,
                         key=lambda a: _num_suffix(str(a), "client") or 0)
        self.server_names = [str(a) for a in servers]
        self.client_names = [str(a) for a in clients]
        self.ns, self.nc = len(servers), len(clients)
        self.addr_index = {self.vs_name: 0}
        self.addr_index.update(
            {s: 1 + i for i, s in enumerate(self.server_names)})
        self.addr_index.update(
            {c: 1 + self.ns + j for j, c in enumerate(self.client_names)})
        pairs = [_workload_pairs(workers[a], a) for a in clients]
        sizes = {len(p) for p in pairs}
        if len(sizes) != 1:
            raise NoTensorTwin(
                f"per-client workload sizes differ ({sizes})")
        self.w = sizes.pop()
        self.pairs = pairs
        self.key = ("primarybackup", self.vs_name,
                    tuple(self.server_names), tuple(self.client_names),
                    tuple(repr(c) for p in pairs for c, _ in p))

    def initial_caps(self):
        return 32, 4

    def build_protocol(self, net_cap, timer_cap):
        from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol

        p = make_pb_protocol(ns=self.ns, n_clients=self.nc, w=self.w,
                             net_cap=net_cap, timer_cap=timer_cap)
        return dataclasses.replace(
            p, decode_message=self._decode_message,
            decode_timer=self._decode_timer)

    # ------------------------------------------------------------ decoders

    def _addr(self, idx):
        from dslabs_tpu.core.address import LocalAddress

        names = [self.vs_name] + self.server_names + self.client_names
        return LocalAddress(names[int(idx)])

    def _view(self, vn, prim, back):
        from dslabs_tpu.labs.primarybackup.viewserver import View

        return View(int(vn),
                    self._addr(prim) if prim else None,
                    self._addr(back) if back else None)

    def _amo(self, c, s):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.amo import AMOCommand

        return AMOCommand(self.pairs[c][s - 1][0],
                          LocalAddress(self.client_names[c]), s)

    def _decode_message(self, rec):
        from dslabs_tpu.labs.clientserver.amo import AMOResult
        from dslabs_tpu.labs.primarybackup import pb as P
        from dslabs_tpu.labs.primarybackup import viewserver as V
        from dslabs_tpu.tpu.protocols.primarybackup import (
            FWD, FWDACK, GETVIEW, PING, REPLY, REQ, VIEWREPLY, XFER,
            XFERACK)
        from dslabs_tpu.tpu.trace import MessageTemplate

        r = [int(x) for x in rec]
        tag, frm, to, p = r[0], r[1], r[2], r[3:]
        fa, ta = self._addr(frm), self._addr(to)
        if tag == PING:
            return fa, ta, V.Ping(p[0])
        if tag == GETVIEW:
            return fa, ta, V.GetView()
        if tag == VIEWREPLY:
            return fa, ta, V.ViewReply(self._view(p[0], p[1], p[2]))
        if tag == REQ:
            return fa, ta, P.Request(self._amo(p[0], p[1]))
        if tag == REPLY:
            c, s = p[0], p[1]
            fallback = P.Reply(AMOResult(self.pairs[c][s - 1][1], s))
            return fa, ta, MessageTemplate(
                P.Reply, fallback,
                lambda m, s=s: m.result.sequence_num == s)
        if tag == FWD:
            return fa, ta, P.ForwardRequest(p[0], self._amo(p[1], p[2]))
        if tag == FWDACK:
            return fa, ta, P.ForwardAck(p[0], self._amo(p[1], p[2]))
        if tag == XFER:
            vn, amo = p[0], p[3:3 + self.nc]

            def match(m, vn=vn, amo=tuple(amo)):
                from dslabs_tpu.core.address import LocalAddress

                if m.view.view_num != vn:
                    return False
                for c, want in enumerate(amo):
                    got = m.app.last.get(
                        LocalAddress(self.client_names[c]))
                    if (got[0] if got else 0) != want:
                        return False
                return True

            return fa, ta, MessageTemplate(P.StateTransfer, None, match)
        if tag == XFERACK:
            return fa, ta, P.StateTransferAck(p[0])
        raise NoTensorTwin(f"unknown pb message tag {tag}")

    def _decode_timer(self, node_idx, rec):
        from dslabs_tpu.labs.primarybackup import pb as P
        from dslabs_tpu.labs.primarybackup import viewserver as V
        from dslabs_tpu.tpu.protocols.primarybackup import (
            CLIENT_MS, PING_MS, PINGCHECK_MS, T_CLIENT, T_PING,
            T_PINGCHECK)

        tag, p0 = int(rec[0]), int(rec[3])
        a = self._addr(node_idx)
        if tag == T_PINGCHECK:
            return a, V.PingCheckTimer(), PINGCHECK_MS, PINGCHECK_MS
        if tag == T_PING:
            return a, P.PingTimer(), PING_MS, PING_MS
        if tag == T_CLIENT:
            c = int(node_idx) - 1 - self.ns
            return a, P.ClientTimer(self._amo(c, p0)), CLIENT_MS, CLIENT_MS
        raise NoTensorTwin(f"unknown pb timer tag {tag}")

    # ---------------------------------------------------------- predicates

    def predicate(self, tkey):
        import jax.numpy as jnp

        from dslabs_tpu.tpu.protocols.primarybackup import make_pb_protocol  # noqa: F401

        kind = tkey[0]
        ns, nc, w = self.ns, self.nc, self.w
        VSW = 5 + 2 * ns
        SW = 6 + nc
        cb = VSW + ns * SW

        def k(s, c):
            return s["nodes"][cb + c * 4]

        if kind in ("RESULTS_OK", "RESULTS_LINEARIZABLE",
                    "ALL_RESULTS_SAME"):
            fn = lambda s: k(s, 0) >= 0  # noqa: E731
            fn.value_level = True        # object-side re-check on exhaust
            return fn
        if kind == "CLIENTS_DONE":
            def fn(s):
                done = jnp.asarray(True)
                for c in range(nc):
                    done = done & (k(s, c) == w + 1)
                return done
            return fn
        if kind == "NONE_DECIDED":
            def fn(s):
                nd = jnp.asarray(True)
                for c in range(nc):
                    nd = nd & (k(s, c) == 1)
                return nd
            return fn
        if kind == "CLIENT_DONE":
            c = self.client_names.index(str(tkey[1].root_address()))
            return lambda s: k(s, c) == w + 1
        if kind == "CLIENT_HAS_RESULTS":
            c = self.client_names.index(str(tkey[1].root_address()))
            return lambda s: k(s, c) >= tkey[2] + 1
        if kind == "PB_PROMOTED":
            # A named server serves a view with itself primary, no
            # backup, synced (the failover goal, test19).
            pi = self.server_names.index(tkey[1]) + 1

            def fn(s):
                def srv(i, off):
                    return s["nodes"][VSW + i * SW + off]
                return ((srv(pi - 1, 1) == pi) & (srv(pi - 1, 2) == 0)
                        & (srv(pi - 1, 3) == 1)
                        & (srv(pi - 1, 0) > 0))
            return fn
        if kind == "PB_VIEW_SYNCED":
            # The lab tests' staged goal: the NAMED primary reports view
            # vn with (primary, backup) and synced, and the named backup
            # reports vn synced — other servers (often gated off) are
            # not constrained (tests/test_lab2_pb.py view2_synced).
            vn = tkey[1]
            pi = self.server_names.index(tkey[2]) + 1
            bi = self.server_names.index(tkey[3]) + 1

            want_acked = len(tkey) > 4 and tkey[4] == "acked"

            def fn(s):
                def srv(i, off):
                    return s["nodes"][VSW + i * SW + off]
                ok = ((srv(pi - 1, 0) == vn) & (srv(pi - 1, 1) == pi)
                      & (srv(pi - 1, 2) == bi) & (srv(pi - 1, 3) == 1)
                      & (srv(bi - 1, 0) == vn) & (srv(bi - 1, 3) == 1))
                if want_acked:
                    # ViewServer acked flag (lane 3 of the master block,
                    # tpu/protocols/primarybackup.py _unpack).
                    ok = ok & (s["nodes"][3] == 1)
                return ok
            return fn
        return None


@register_adapter
def match_primarybackup(state):
    from dslabs_tpu.labs.primarybackup.pb import PBClient, PBServer
    from dslabs_tpu.labs.primarybackup.viewserver import ViewServer

    servers = state.servers
    workers = state.client_workers()
    if not servers or not workers:
        return None
    kinds = {type(s) for s in servers.values()}
    if kinds != {ViewServer, PBServer}:
        return None
    if not all(isinstance(wk.client, PBClient)
               for wk in workers.values()):
        return None
    return PrimaryBackupBinding(state)
