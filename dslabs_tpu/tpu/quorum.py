"""Quorum aggregation combinators for the protocol spec layer
(ISSUE 20, ROADMAP #1).

The hand-written lab3/lab4 twins all contain the same expert pattern:
a per-instance VOTE BITMAP lane (bit ``i`` = member ``i`` voted), a
bit-twiddling popcount, and a ``2*count > n`` majority test.  This
module lifts that pattern into a declaration — :class:`QuorumCount`
names the node kind (or ``index_group``) being counted over and the
threshold rule — plus the reducers handlers and invariant predicates
use on the lowered lanes:

* ``popcount(bits, n)`` — the hand twins' SWAR popcount, restricted to
  the low ``n`` bits (a vote bitmap over ``n`` members),
* ``count_true(vec)`` / ``majority(vec, n)`` / ``all_of`` / ``any_of``
  — reducers over an ``index_group`` array field (one lane per member).

Declarations live on the spec (``ProtocolSpec(quorums=...)``) so the
compile gate can refuse a quorum over an empty or unknown group
(``SpecError``, the ISSUE 20 edge-case satellite) and so the memo
fingerprint (service/memo.py) distinguishes two protocols differing
only in a threshold.  Handlers reach the RESOLVED form through
``ctx.quorum(name)`` -> :class:`Quorum`: the threshold arithmetic is
spec data, never a handler-local constant, which is what keeps the C5
symmetry argument intact (a popcount is permutation-invariant, a
member-specific bit test is not — see analysis/conformance.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

__all__ = ["QuorumCount", "Quorum", "popcount", "count_true",
           "majority", "all_of", "any_of", "resolve_quorums"]


@dataclasses.dataclass(frozen=True)
class QuorumCount:
    """A declared quorum: count votes ``over`` the instances of one
    node kind (equivalently: over the lanes of any array field whose
    ``index_group`` names that kind) and compare against ``threshold``
    — an int, or one of ``"majority"`` (n//2 + 1), ``"all"`` (n),
    ``"any"`` (1)."""

    name: str
    over: str
    threshold: Union[int, str] = "majority"


@dataclasses.dataclass(frozen=True)
class Quorum:
    """A :class:`QuorumCount` resolved against its spec: ``n`` members,
    ``need`` votes.  The methods are plain jnp reducers usable inside
    handlers (on traced lanes) and predicates (on state views)."""

    name: str
    over: str
    n: int
    need: int

    # ------------------------------------------------------ bitmap form

    def count_bits(self, bits):
        """Popcount of a vote BITMAP lane (bit i = member i voted)."""
        return popcount(bits, self.n)

    def met_bits(self, bits):
        return self.count_bits(bits) >= self.need

    # ------------------------------------------------------- array form

    def count(self, vec):
        """Count of non-zero votes in an ``index_group`` array field."""
        return count_true(vec)

    def met(self, vec):
        return self.count(vec) >= self.need


def popcount(bits, n: int):
    """Bit-population count of the low ``n`` bits of ``bits`` — the
    hand paxos twin's ``_popcount`` SWAR ladder, here as the ONE shared
    lowering every quorum declaration compiles to.  ``n`` is static
    (the group size), so the mask folds at trace time."""
    import jax.numpy as jnp

    v = jnp.asarray(bits, jnp.int32) & ((1 << n) - 1)
    v = v - ((v >> 1) & 0x55555555)
    v = (v & 0x33333333) + ((v >> 2) & 0x33333333)
    v = (v + (v >> 4)) & 0x0F0F0F0F
    return (v * 0x01010101) >> 24


def count_true(vec):
    """Number of non-zero lanes of a per-member array field."""
    import jax.numpy as jnp

    v = jnp.asarray(vec)
    return jnp.sum((v != 0).astype(jnp.int32))


def majority(vec, n: Optional[int] = None):
    """True when a strict majority of the ``n`` members voted."""
    import jax.numpy as jnp

    v = jnp.atleast_1d(jnp.asarray(vec))
    total = n if n is not None else v.shape[0]
    return 2 * count_true(v) > total


def all_of(vec, n: Optional[int] = None):
    import jax.numpy as jnp

    v = jnp.atleast_1d(jnp.asarray(vec))
    total = n if n is not None else v.shape[0]
    return count_true(v) >= total


def any_of(vec):
    return count_true(vec) >= 1


def resolve_quorums(spec) -> dict:
    """Validate + resolve a spec's declared quorums against its node
    kinds.  Raises the structured compile-gate error for a quorum over
    an unknown or EMPTY group (ISSUE 20 satellite: refused loudly, not
    a vacuously-met threshold deep in a search)."""
    from dslabs_tpu.tpu.compiler import SpecError

    counts = {k.name: k.count for k in spec.nodes}
    out = {}
    for q in getattr(spec, "quorums", ()) or ():
        if q.name in out:
            raise SpecError(
                f"duplicate quorum declaration {q.name!r}",
                spec=spec.name, field=q.name, code="C4")
        n = counts.get(q.over)
        if n is None:
            raise SpecError(
                f"quorum {q.name!r} counts over unknown node kind "
                f"{q.over!r} (declared: {sorted(counts)})",
                spec=spec.name, kind=q.over, field=q.name, code="C4")
        if n <= 0:
            raise SpecError(
                f"quorum {q.name!r} counts over EMPTY group {q.over!r} "
                f"(0 instances) — every threshold is vacuous; declare "
                f"the group with instances or drop the quorum",
                spec=spec.name, kind=q.over, field=q.name, code="C4")
        if isinstance(q.threshold, str):
            need = {"majority": n // 2 + 1, "all": n, "any": 1}.get(
                q.threshold)
            if need is None:
                raise SpecError(
                    f"quorum {q.name!r} has unknown threshold rule "
                    f"{q.threshold!r} (use an int, 'majority', 'all' "
                    f"or 'any')", spec=spec.name, field=q.name,
                    code="C4")
        else:
            need = int(q.threshold)
            if not 1 <= need <= n:
                raise SpecError(
                    f"quorum {q.name!r} threshold {need} outside "
                    f"[1, {n}] for group {q.over!r}",
                    spec=spec.name, field=q.name, code="C4")
        out[q.name] = Quorum(q.name, q.over, n, need)
    return out
