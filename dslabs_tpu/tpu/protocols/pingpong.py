"""Tensorised twin of lab 0 ping-pong (SURVEY §8.3 — the minimum
end-to-end slice).

Object model being mirrored (dslabs_tpu/labs/pingpong/pingpong.py +
testing/client_worker.py): a stateless PingServer echoing Ping(i) -> Pong(i)
and a ClientWorker-wrapped PingClient walking a ``hi-%i`` workload of W
commands with a (10,10) retry timer.  The combined client state collapses to
one integer k: "waiting on command k" (k in 1..W) or done (W+1) — the
worker pumps the next command inside the same handler, so intermediate
states never appear in the search graph (ClientWorker.java:174-235).

Lanes:
  nodes  = [k]                                   (server is stateless)
  msg    = [tag, i]        tag 0 = PingRequest -> server, 1 = PongReply
  timer  = [tag, min, max, i]                    PingTimer(i), (10, 10)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_pingpong_protocol", "make_exhaustive_pingpong",
           "SERVER", "CLIENT"]

SERVER, CLIENT = 0, 1
REQ, REPLY = 0, 1
PING_MS = 10


def make_pingpong_protocol(workload_size: int) -> TensorProtocol:
    w = workload_size
    mw, tw = 2, 4
    max_sends, max_sets = 1, 1

    # ---- object-twin decoders (tpu/trace.py): canonical parity config —
    # server "pingserver", client "client1", workload hi-{i}
    # (tests/test_tpu_engine.py).

    def decode_message(rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.pingpong.pingpong import (Ping, PingRequest,
                                                       Pong, PongReply)

        tag, i = int(rec[0]), int(rec[1])
        server = LocalAddress("pingserver")
        client = LocalAddress("client1")
        if tag == REQ:
            return client, server, PingRequest(Ping(f"hi-{i}"))
        return server, client, PongReply(Pong(f"hi-{i}"))

    def decode_timer(node_idx, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.pingpong.pingpong import Ping, PingTimer

        return (LocalAddress("client1"), PingTimer(Ping(f"hi-{int(rec[3])}")),
                PING_MS, PING_MS)

    def init_nodes():
        return np.array([1], np.int32)  # waiting on command 1

    def init_messages():
        return np.array([[REQ, 1]], np.int32)

    def init_timers():
        return np.array([[CLIENT, 1, PING_MS, PING_MS, 1]], np.int32)

    def no_sends():
        return jnp.full((max_sends, mw), SENTINEL, jnp.int32)

    def no_sets():
        return jnp.full((max_sets, 1 + tw), SENTINEL, jnp.int32)

    def send_request(i):
        return jnp.stack([jnp.full((), REQ, jnp.int32), i])[None, :]

    def set_ping_timer(i):
        return jnp.stack([jnp.full((), CLIENT, jnp.int32),
                          jnp.full((), 1, jnp.int32),
                          jnp.full((), PING_MS, jnp.int32),
                          jnp.full((), PING_MS, jnp.int32), i])[None, :]

    def step_message(nodes, msg):
        k = nodes[0]
        tag, i = msg[0], msg[1]

        # PingRequest at the server: echo a PongReply (PingServer.java:26-31).
        is_req = tag == REQ
        req_sends = jnp.where(is_req,
                              jnp.stack([jnp.full((), REPLY, jnp.int32), i])[None, :],
                              no_sends())

        # PongReply at the client: if it answers the in-flight ping, the
        # worker records the result and pumps the next command.
        matches = (tag == REPLY) & (k == i) & (k <= w)
        k2 = jnp.where(matches, k + 1, k)
        has_next = matches & (k2 <= w)
        reply_sends = jnp.where(has_next, send_request(k2), no_sends())
        reply_sets = jnp.where(has_next, set_ping_timer(k2), no_sets())

        nodes2 = nodes.at[0].set(k2)
        sends = jnp.where(is_req, req_sends, reply_sends)
        sets = jnp.where(is_req, no_sets(), reply_sets)
        return nodes2, sends, sets

    def step_timer(nodes, node_idx, timer):
        k = nodes[0]
        i = timer[3]
        live = (node_idx == CLIENT) & (k == i) & (k <= w)
        sends = jnp.where(live, send_request(i), no_sends())
        sets = jnp.where(live, set_ping_timer(i), no_sets())
        return nodes, sends, sets

    def msg_dest(msg):
        return jnp.where(msg[0] == REQ, SERVER, CLIENT)

    def clients_done(state):
        return state["nodes"][0] == w + 1

    def results_ok(state):
        return jnp.full((), True)  # the echo protocol cannot mis-answer

    return TensorProtocol(
        name=f"pingpong-w{w}",
        n_nodes=2,
        node_width=1,
        msg_width=mw,
        timer_width=tw,
        net_cap=2 * w + 2,
        timer_cap=w + 2,
        max_sends=max_sends,
        max_sets=max_sets,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        invariants={"RESULTS_OK": results_ok},
        goals={"CLIENTS_DONE": clients_done},
        decode_message=decode_message,
        decode_timer=decode_timer,
    )


def make_exhaustive_pingpong(workload_size: int = 2) -> TensorProtocol:
    """The goal-pruned exhaustive variant: CLIENTS_DONE becomes a prune
    so a strict search measures full-space parity instead of a
    first-goal race — the canonical small JOB UNIT the checking
    service, its chaos-isolation soak, and the bench's ``service``
    phase all submit (a ``"module:callable"`` factory spec that crosses
    the warden spawn boundary with no transform needed)."""
    import dataclasses

    p = make_pingpong_protocol(workload_size)
    return dataclasses.replace(
        p, goals={}, prunes={"CLIENTS_DONE": p.goals["CLIENTS_DONE"]})
