"""Tensorised twin of lab 1 exactly-once client/server.

Object model being mirrored (dslabs_tpu/labs/clientserver/clientserver.py:
SimpleServer = AMOApplication(KVStore), SimpleClient with a 100 ms retry
timer; reference spec ClientServerPart2Test.java:175-281): ``n_clients``
ClientWorker-wrapped clients each Put their own key W times.

State collapse (same discipline as the generated paxos twin,
tpu/specs_lab3.py):
under this workload every object-state component is determined by two
small integers per client —

  a_c  server-side AMO last-executed seq for client c (KVStore key_c and
       the AMO result cache are functions of a_c: commands arrive in
       client order, the AMO layer executes a prefix 1..a_c),
  k_c  client progress: waiting on command k (ClientWorker pumps the next
       command inside the reply handler, ClientWorker.java:174-235), or
       done (W+1).

Lanes:
  nodes  = [a_0..a_{NC-1}, k_0..k_{NC-1}]   node 0 = server, 1+c = client c
  msg    = [tag, c, seq]                    REQ -> server, REPLY -> client c
  timer  = [tag, min, max, seq]             ClientTimer on node 1+c
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_clientserver_protocol"]

REQ, REPLY = 0, 1
T_CLIENT = 1
CLIENT_MS = 100  # lab1 Timers.java ClientTimer


def make_clientserver_protocol(n_clients: int = 1, w: int = 1,
                               net_cap: int = 16,
                               timer_cap: int = 4) -> TensorProtocol:
    NC = n_clients
    MW, TW = 3, 4
    NW = 2 * NC
    N_NODES = 1 + NC

    def msg_row(cond, tag, c, seq):
        rec = jnp.stack([jnp.asarray(tag, jnp.int32),
                         jnp.asarray(c, jnp.int32),
                         jnp.asarray(seq, jnp.int32)])
        return jnp.where(cond, rec, jnp.full((MW,), SENTINEL, jnp.int32))[None]

    def timer_row(cond, c, seq):
        rec = jnp.stack([jnp.asarray(1 + c, jnp.int32),
                         jnp.asarray(T_CLIENT, jnp.int32),
                         jnp.asarray(CLIENT_MS, jnp.int32),
                         jnp.asarray(CLIENT_MS, jnp.int32),
                         jnp.asarray(seq, jnp.int32)])
        return jnp.where(cond, rec,
                         jnp.full((1 + TW,), SENTINEL, jnp.int32))[None]

    def step_message(nodes, msg):
        tag, c, s = msg[0], msg[1], msg[2]
        ci = c.clip(0, NC - 1)

        # ---- server: handle_Request (SimpleServer.handle_Request; AMO
        # executes fresh seqs, replies for fresh or exactly-cached seqs)
        is_req = tag == REQ
        a = nodes[ci]
        fresh = is_req & (s > a)
        nodes = nodes.at[ci].set(jnp.where(fresh, s, a).astype(jnp.int32))
        reply = is_req & (s >= a)          # fresh -> reply; s == a -> cached
        sends = msg_row(reply, REPLY, c, s)

        # ---- client c: handle_Reply (ClientWorker pumps the next command)
        is_rep = tag == REPLY
        k = nodes[NC + ci]
        match = is_rep & (s == k) & (k <= w)
        k2 = jnp.where(match, k + 1, k)
        nodes = nodes.at[NC + ci].set(k2.astype(jnp.int32))
        has_next = match & (k2 <= w)
        sends = jnp.minimum(sends, msg_row(has_next, REQ, c, k2))
        tsets = timer_row(has_next, ci, k2)
        return nodes, sends, tsets

    def step_timer(nodes, node_idx, timer):
        # ClientTimer on node 1+c: retry iff still waiting on that seq
        # (SimpleClient.on_ClientTimer).
        tag, s = timer[0], timer[3]
        ci = (node_idx - 1).clip(0, NC - 1)
        k = nodes[NC + ci]
        live = (node_idx >= 1) & (tag == T_CLIENT) & (s == k) & (k <= w)
        sends = msg_row(live, REQ, ci, k)
        tsets = timer_row(live, ci, k)
        return nodes, sends, tsets

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        nodes[NC:] = 1            # every client waiting on command 1
        return nodes

    def init_messages():
        return np.array([[REQ, c, 1] for c in range(NC)], np.int32)

    def init_timers():
        return np.array([[1 + c, T_CLIENT, CLIENT_MS, CLIENT_MS, 1]
                         for c in range(NC)], np.int32)

    def msg_dest(msg):
        return jnp.where(msg[0] == REQ, 0, 1 + msg[1])

    # ---- object-twin decoders (tpu/trace.py): the canonical parity
    # config — server "server", clients "client{c}", workload
    # PUT:key{c}:v{i} (tests/test_tpu_engine.py).

    def _amo_cmd(c, s):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.amo import AMOCommand
        from dslabs_tpu.labs.clientserver.kvstore import Put

        return AMOCommand(Put(f"key{c}", f"v{s}"), LocalAddress(f"client{c}"),
                          s)

    def decode_message(rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.amo import AMOResult
        from dslabs_tpu.labs.clientserver.clientserver import Reply, Request
        from dslabs_tpu.labs.clientserver.kvstore import PutOk

        tag, c, s = int(rec[0]), int(rec[1]), int(rec[2])
        server = LocalAddress("server")
        client = LocalAddress(f"client{c}")
        if tag == REQ:
            return client, server, Request(_amo_cmd(c, s))
        return server, client, Reply(AMOResult(PutOk(), s))

    def decode_timer(node_idx, rec):
        from dslabs_tpu.core.address import LocalAddress
        from dslabs_tpu.labs.clientserver.clientserver import ClientTimer

        c = node_idx - 1
        s = int(rec[3])
        return (LocalAddress(f"client{c}"), ClientTimer(_amo_cmd(c, s)),
                CLIENT_MS, CLIENT_MS)

    def clients_done(state):
        done = jnp.asarray(True)
        for c in range(NC):
            done = done & (state["nodes"][NC + c] == w + 1)
        return done

    return TensorProtocol(
        name=f"clientserver-c{NC}-w{w}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=1,
        max_sets=1,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
        decode_message=decode_message,
        decode_timer=decode_timer,
    )
