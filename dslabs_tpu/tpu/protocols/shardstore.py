"""Tensorised twin of lab 4's sharded KV store for the search-test
configurations (ShardStorePart1Test.test10-12 shape): G groups of ONE
server each, one shard master, one client, the config controller and
master timers frozen (tests/test_lab4_shardstore.py test10-12 mirror
these settings from ShardStoreBaseTest.java:209-220).

Why the state collapses (all against the object implementations in
dslabs_tpu/labs/shardedstore/shardstore.py and labs/paxos/paxos.py):

* A one-server Paxos group decides synchronously: ``_send_to_all``
  delivers the leader's own P1a/P2a/P2b locally (paxos.py:238-247),
  majority = 1, so a proposal is chosen, executed, AND garbage-collected
  inside the original handler call (exec -> _leader_exec_update ->
  maybe_gc clears through the executed prefix when n == 1).  The
  replicated log is always empty in every reachable state — no log
  lanes; what remains is the decided-slot COUNT, the heard_from_leader
  flag (set by the self-delivered P2a, cleared by ElectionTimer), and
  the constant ballot from the immediate self-election at init.

* The shard master (PaxosServer + ShardMaster app, timers frozen) logs
  every FRESH Query — handle_PaxosRequest AMO-wraps read-only commands
  like any other (paxos.py:326-360).  After the staged Joins its config
  list is STATIC ([cfg0] for G=1; [cfg0, cfg1] for G=2 — one config per
  Join), so a reply's payload is f(query arg): arg < 0 or beyond the
  list -> the latest config, else configs[arg] (shardmaster.py Query).

* The config walk (G=2): each group server queries for config
  _next_config_num() and installs replies in order None -> cfg0 -> cfg1
  (shardstore.py _apply_new_config).  Installing cfg1 at group 1 stores
  a SNAPSHOT of the lost shards' kv + the full AMO map in ``outgoing``;
  every later QueryTimer re-sends the SAME stored ShardMove, so the
  move's content is one integer: group 1's last-executed client seq at
  install time.  Group 2 proposes InstallShards on a matching move
  (owned |= shards, AMO merged as a per-client max), acks, and group 1's
  MoveDone clears outgoing.  While a handoff is pending,
  ``_reconfig_done`` gates further queries (on_QueryTimer) and config
  installs.

* The client always queries with arg -1, so it only ever learns the
  LATEST config — one has-config bit — and routes commands by that
  final mapping; a group that does not yet cover a command's shard
  answers WrongGroup (config current, shard not mine) or stays silent
  (shard mine but still in flight), both mirrored per scfg/in_flag.

Node lanes (node order: 0 = master, 1..G = group servers, G+1 = client):
  master  [mc, mamo_c, mamo_s1..mamo_sG]   decided count + AMO per source
  server g [scfg, samo, scount, sh, sq, out_flag, out_samo, in_flag]
    scfg: 0 = no config, i+1 = configs[i] installed
  client  [k, cfg, cq]                     workload index (W+1 = done),
                                           latest config known, query seq

Message lanes [tag, a, b, c]:
  QRY   [src, seq, arg]      PaxosRequest(AMOCommand(Query(arg), src, seq))
                             src: 0 = client, g = server g
  QREP  [dst, seq, kind]     PaxosReply(AMOResult(configs[kind], seq))
  SSREQ [k, 0, 0]            ShardStoreRequest(AMOCommand(cmd_k, client, k))
  SSREP [k, 0, 0]            ShardStoreReply(AMOResult(result_k, k))
  WG    [k, 0, 0]            WrongGroup(k)
  SM    [to_g, samo, 0]      ShardMove(cfg1, from g1, shards, snapshot)
  SMACK [to_g, 0, 0]         ShardMoveAck(cfg1, shards)
Timer lanes [tag, min, max, p0]: CLIENT(seq) / QUERY / ELECTION / HEARTBEAT.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_shardstore_protocol"]

QRY, QREP, SSREQ, SSREP, WG, SM, SMACK = range(7)
T_CLIENT, T_QUERY, T_ELECTION, T_HEARTBEAT = 1, 2, 3, 4

CLIENT_MS = 100     # shardstore.py CLIENT_RETRY_MILLIS
QUERY_MS = 50       # shardstore.py QUERY_MILLIS
ELECTION_MIN, ELECTION_MAX = 150, 300   # paxos.py
HEARTBEAT_MS = 50


def make_shardstore_protocol(groups_of: Sequence[int],
                             net_cap: int = 48,
                             timer_cap: int = 6) -> TensorProtocol:
    """``groups_of[k-1]`` = the group (1-based) owning workload command
    k's key under the FINAL config — precomputed on the host with the
    same ShardMaster rebalance the object system runs (see
    tests/test_tpu_lab4.py).  G = max(groups_of); with G = 2 the config
    walk and the g1 -> g2 handoff are modelled (groups are built by
    successive Joins, so every shard a 2-group config assigns to g2 was
    g1's under cfg0)."""
    W = len(groups_of)
    G = max(groups_of)
    assert min(groups_of) >= 1
    assert G <= 2, "3+-group configs need multi-hop handoff modelling"
    N_CFG = G                       # one config per staged Join
    MW, TW = 4, 4
    NW = (2 + G) + 8 * G + 3
    N_NODES = 1 + G + 1
    CLIENT = G + 1

    # lane offsets
    M_MC, M_AMOC, M_AMOS = 0, 1, 2            # master (M_AMOS + g-1)
    SRV = 2 + G                               # server g base: SRV + 8*(g-1)
    C_K = SRV + 8 * G
    C_CFG, C_CQ = C_K + 1, C_K + 2
    # server lane offsets within a block
    S_CFG, S_AMO, S_CNT, S_H, S_Q, S_OUT, S_OSAMO, S_IN = range(8)

    def srv(g, off):
        return SRV + 8 * (g - 1) + off

    def grp_of(k):
        """Traced workload index -> owning group under the final config
        (static where-chain)."""
        out = jnp.asarray(groups_of[0], jnp.int32)
        for kk in range(2, W + 1):
            out = jnp.where(k == kk, groups_of[kk - 1], out)
        return out

    def msg_row(cond, tag, a, b=0, c=0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32) for x in (tag, a, b, c)])
        return jnp.where(cond, rec, jnp.full((MW,), SENTINEL, jnp.int32))[None]

    def timer_row(cond, node, tag, mn, mx, p0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32)
                         for x in (node, tag, mn, mx, p0)])
        return jnp.where(cond, rec,
                         jnp.full((1 + TW,), SENTINEL, jnp.int32))[None]

    blank_msg = jnp.full((1, MW), SENTINEL, jnp.int32)
    blank_set = jnp.full((1, 1 + TW), SENTINEL, jnp.int32)

    # Which config index the master serves for a query arg
    # (shardmaster.py Query: arg < 0 or >= len -> latest).
    def served_kind(arg):
        latest = N_CFG - 1
        kind = jnp.where((arg < 0) | (arg >= N_CFG), latest, arg)
        return kind.astype(jnp.int32)

    # Does group g own command k's shard under configs[idx] (0-based)?
    # cfg0 assigns everything to group 1; the final config follows
    # groups_of.  "mine" = the config's assignment; "owned" additionally
    # needs the handoff to have completed (S_IN == 0 for gained shards).
    def cfg_mine(g, cfg_idx, k):
        under_final = grp_of(k) == g
        if g == 1:
            return jnp.where(cfg_idx == 0, True, under_final)
        return jnp.where(cfg_idx == 0, False, under_final)

    # ------------------------------------------------------------- handlers

    def step_message(nodes, msg):
        tag, a, b, c = msg[0], msg[1], msg[2], msg[3]
        sends = []
        tsets = []

        # ---- QRY -> master (paxos.py handle_PaxosRequest; n=1: fresh
        # commands decide+execute+GC inline)
        is_qry = tag == QRY
        src, seq, arg = a, b, c
        for sidx in range(0, G + 1):
            lane = M_AMOC if sidx == 0 else M_AMOS + sidx - 1
            here = is_qry & (src == sidx)
            last = nodes[lane]
            fresh = here & (seq > last)
            nodes = nodes.at[lane].set(
                jnp.where(fresh, seq, last).astype(jnp.int32))
            nodes = nodes.at[M_MC].set(
                jnp.where(fresh, nodes[M_MC] + 1,
                          nodes[M_MC]).astype(jnp.int32))
            # reply for fresh or exactly-cached seq; payload = the served
            # config (dup deliveries carry the same arg, so recomputing
            # the kind from the message matches the cached result)
            sends.append(msg_row(here & (seq >= last), QREP, src, seq,
                                 served_kind(arg)))

        # ---- QREP -> client: adopt the (always latest) config if newer,
        # then send the pending command (shardstore.py client
        # handle_PaxosReply + _send_pending)
        is_qrep_c = (tag == QREP) & (a == 0)
        k = nodes[C_K]
        adopt = is_qrep_c & (nodes[C_CFG] == 0)
        nodes = nodes.at[C_CFG].set(
            jnp.where(adopt, 1, nodes[C_CFG]).astype(jnp.int32))
        sends.append(msg_row(adopt & (k <= W), SSREQ, k))

        # ---- QREP -> server g: propose NewConfig iff the carried config
        # is exactly _next_config_num() and reconfig is done
        # (shardstore.py handle_PaxosReply + _apply_new_config)
        for g in range(1, G + 1):
            here = (tag == QREP) & (a == g)
            kind = c                                  # configs[kind]
            scfg = nodes[srv(g, S_CFG)]
            done = ((nodes[srv(g, S_OUT)] == 0)
                    & (nodes[srv(g, S_IN)] == 0))
            install = here & (kind == scfg) & (scfg < N_CFG) & done
            # installing the FINAL config starts the handoff (only group
            # transitions that move shards: g1 loses, g2 gains; the first
            # config never moves anything)
            is_final = install & (scfg == N_CFG - 1) & (N_CFG > 1)
            if g == 1 and G > 1:
                nodes = nodes.at[srv(g, S_OUT)].set(
                    jnp.where(is_final, 1,
                              nodes[srv(g, S_OUT)]).astype(jnp.int32))
                nodes = nodes.at[srv(g, S_OSAMO)].set(
                    jnp.where(is_final, nodes[srv(g, S_AMO)],
                              nodes[srv(g, S_OSAMO)]).astype(jnp.int32))
                # leader installs -> _send_moves inline
                sends.append(msg_row(is_final, SM, 2,
                                     nodes[srv(g, S_AMO)]))
            elif g == 2:
                nodes = nodes.at[srv(g, S_IN)].set(
                    jnp.where(is_final, 1,
                              nodes[srv(g, S_IN)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_CFG)].set(
                jnp.where(install, scfg + 1,
                          nodes[srv(g, S_CFG)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_CNT)].set(
                jnp.where(install, nodes[srv(g, S_CNT)] + 1,
                          nodes[srv(g, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_H)].set(
                jnp.where(install, 1, nodes[srv(g, S_H)]).astype(jnp.int32))

        # ---- SSREQ -> server grp_of(k): ALWAYS proposes (relay-mode
        # chosen entries are not deduped, paxos.py:349-355) -> count+1,
        # heard; execution is gated by config coverage and ownership
        # (shardstore.py _execute_client_command)
        is_ss = tag == SSREQ
        kk = a
        kg = grp_of(kk)
        for g in range(1, G + 1):
            here = is_ss & (kg == g)
            nodes = nodes.at[srv(g, S_CNT)].set(
                jnp.where(here, nodes[srv(g, S_CNT)] + 1,
                          nodes[srv(g, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, S_H)].set(
                jnp.where(here, 1, nodes[srv(g, S_H)]).astype(jnp.int32))
            scfg = nodes[srv(g, S_CFG)]
            has_cfg = scfg >= 1
            mine = cfg_mine(g, (scfg - 1).clip(0, N_CFG - 1), kk) & has_cfg
            # wrong group: current config exists but shard is not mine
            sends.append(msg_row(here & has_cfg & ~mine, WG, kk))
            # mine but still incoming -> silent (client retries); only
            # group 2 ever gains shards, in one block per handoff
            if g == 2 and G > 1:
                owned = mine & (nodes[srv(g, S_IN)] == 0)
            else:
                owned = mine
            samo = nodes[srv(g, S_AMO)]
            execd = here & owned & (kk > samo)        # owned ⊆ mine
            nodes = nodes.at[srv(g, S_AMO)].set(
                jnp.where(execd, kk, samo).astype(jnp.int32))
            sends.append(msg_row(here & owned & (kk >= samo), SSREP, kk))

        # ---- SSREP -> client (ClientWorker pumps the next command)
        is_rep = tag == SSREP
        match = is_rep & (a == k) & (k <= W)
        k2 = jnp.where(match, k + 1, k)
        nodes = nodes.at[C_K].set(k2.astype(jnp.int32))
        has_next = match & (k2 <= W)
        sends.append(msg_row(has_next, SSREQ, k2))
        tsets.append(timer_row(has_next, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k2))

        # ---- WG -> client: re-query (shardstore.py handle_WrongGroup)
        is_wg = (tag == WG) & (a == k) & (k <= W)
        cq = nodes[C_CQ]
        nodes = nodes.at[C_CQ].set(
            jnp.where(is_wg, cq + 1, cq).astype(jnp.int32))
        sends.append(msg_row(is_wg, QRY, 0, cq + 1, -1))

        # ---- SM -> group 2: propose InstallShards when at the final
        # config with the shards still incoming; re-ack when already
        # installed; ignore when behind (shardstore.py handle_ShardMove)
        if G > 1:
            is_sm = (tag == SM) & (a == 2)
            scfg2 = nodes[srv(2, S_CFG)]
            at_final = scfg2 == N_CFG
            inst = is_sm & at_final & (nodes[srv(2, S_IN)] == 1)
            reack = is_sm & at_final & (nodes[srv(2, S_IN)] == 0)
            nodes = nodes.at[srv(2, S_CNT)].set(
                jnp.where(inst, nodes[srv(2, S_CNT)] + 1,
                          nodes[srv(2, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(2, S_H)].set(
                jnp.where(inst, 1, nodes[srv(2, S_H)]).astype(jnp.int32))
            # AMO merge: per-client max of own and the snapshot's
            samo2 = nodes[srv(2, S_AMO)]
            nodes = nodes.at[srv(2, S_AMO)].set(
                jnp.where(inst, jnp.maximum(samo2, b),
                          samo2).astype(jnp.int32))
            nodes = nodes.at[srv(2, S_IN)].set(
                jnp.where(inst, 0, nodes[srv(2, S_IN)]).astype(jnp.int32))
            sends.append(msg_row(inst | reack, SMACK, 1))

            # ---- SMACK -> group 1: propose MoveDone while the handoff
            # is outstanding (shardstore.py handle_ShardMoveAck)
            is_ack = (tag == SMACK) & (a == 1)
            fin = is_ack & (nodes[srv(1, S_OUT)] == 1)
            nodes = nodes.at[srv(1, S_CNT)].set(
                jnp.where(fin, nodes[srv(1, S_CNT)] + 1,
                          nodes[srv(1, S_CNT)]).astype(jnp.int32))
            nodes = nodes.at[srv(1, S_H)].set(
                jnp.where(fin, 1, nodes[srv(1, S_H)]).astype(jnp.int32))
            nodes = nodes.at[srv(1, S_OUT)].set(
                jnp.where(fin, 0, nodes[srv(1, S_OUT)]).astype(jnp.int32))

        sends = jnp.concatenate(sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        sends = []
        tsets = []

        # ---- ClientTimer (shardstore.py on_ClientTimer): re-query (+1
        # more query when there is no config yet — _send_pending falls
        # back to _query_config) and re-send the pending command.
        k = nodes[C_K]
        live = ((node_idx == CLIENT) & (tag == T_CLIENT) & (p0 == k)
                & (k <= W))
        cq = nodes[C_CQ]
        has_cfg = nodes[C_CFG] == 1
        cq2 = jnp.where(live, jnp.where(has_cfg, cq + 1, cq + 2), cq)
        nodes = nodes.at[C_CQ].set(cq2.astype(jnp.int32))
        sends.append(msg_row(live, QRY, 0, cq + 1, -1))
        sends.append(jnp.where(has_cfg,
                               msg_row(live, SSREQ, k)[0],
                               msg_row(live, QRY, 0, cq + 2, -1)[0])[None])
        tsets.append(timer_row(live, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k))

        for g in range(1, G + 1):
            here = node_idx == g
            # ---- QueryTimer (shardstore.py on_QueryTimer): the query
            # itself is gated on _reconfig_done; _send_moves always runs
            # (re-sends the stored ShardMove while a handoff is pending).
            is_q = here & (tag == T_QUERY)
            done = ((nodes[srv(g, S_OUT)] == 0)
                    & (nodes[srv(g, S_IN)] == 0))
            ask = is_q & done
            sq = nodes[srv(g, S_Q)]
            nodes = nodes.at[srv(g, S_Q)].set(
                jnp.where(ask, sq + 1, sq).astype(jnp.int32))
            sends.append(msg_row(ask, QRY, g, sq + 1,
                                 nodes[srv(g, S_CFG)]))
            if g == 1 and G > 1:
                sends.append(msg_row(is_q & (nodes[srv(1, S_OUT)] == 1),
                                     SM, 2, nodes[srv(1, S_OSAMO)]))
            tsets.append(timer_row(is_q, g, T_QUERY, QUERY_MS, QUERY_MS, 0))

            # ---- ElectionTimer (paxos.py on_ElectionTimer): the lone
            # server is its own decided leader; only heard resets.
            is_el = here & (tag == T_ELECTION)
            nodes = nodes.at[srv(g, S_H)].set(
                jnp.where(is_el, 0, nodes[srv(g, S_H)]).astype(jnp.int32))
            tsets.append(timer_row(is_el, g, T_ELECTION,
                                   ELECTION_MIN, ELECTION_MAX, 0))

            # ---- HeartbeatTimer: no peers, nothing in flight — pure
            # re-arm (state unchanged).
            is_hb = here & (tag == T_HEARTBEAT)
            tsets.append(timer_row(is_hb, g, T_HEARTBEAT,
                                   HEARTBEAT_MS, HEARTBEAT_MS, 0))

        sends = jnp.concatenate(sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    # Row budgets = the TOTAL rows each step function appends (rows are
    # individually condition-masked; the pad/slice below must never
    # truncate a real row).  step_message: (G+1) QREP + 1 client SSREQ +
    # G-block QREP rows (1 SM for g1 when G>1) + 2G SSREQ rows (WG +
    # SSREP per g) + 1 pumped SSREQ + CT + 1 WG-requery + (SMACK) rows.
    MAX_SENDS = (G + 1) + 1 + (1 if G > 1 else 0) + 2 * G + 1 + 1 + (
        1 if G > 1 else 0)
    MAX_SETS = 1 + 3 * G        # client CT + per-server query/election/hb

    # ------------------------------------------------------------- initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        nodes[M_MC] = G          # one decided Join per group
        nodes[C_K] = 1           # first command pending
        # init() queries once; send_command -> _send_pending with no
        # config falls back to _query_config and queries AGAIN
        # (shardstore.py:624-650), so two queries are already in flight.
        nodes[C_CQ] = 2
        return nodes

    def init_messages():
        return np.array([[QRY, 0, 1, -1], [QRY, 0, 2, -1]], np.int32)

    def init_timers():
        rows = []
        for g in range(1, G + 1):
            # ShardStoreServer.init: paxos.init (Election, then the
            # immediate self-election arms Heartbeat), then QueryTimer.
            rows.append([g, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
            rows.append([g, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, 0])
            rows.append([g, T_QUERY, QUERY_MS, QUERY_MS, 0])
        rows.append([CLIENT, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(rows, np.int32)

    def msg_dest(msg):
        tag, a = msg[0], msg[1]
        dest = jnp.asarray(0, jnp.int32)                      # QRY -> master
        dest = jnp.where(tag == QREP,
                         jnp.where(a == 0, CLIENT, a), dest)
        dest = jnp.where(tag == SSREQ, grp_of(msg[1]), dest)
        dest = jnp.where((tag == SSREP) | (tag == WG), CLIENT, dest)
        dest = jnp.where((tag == SM) | (tag == SMACK), a, dest)
        return dest

    def clients_done(state):
        return state["nodes"][C_K] == W + 1

    return TensorProtocol(
        name=f"shardstore-g{G}-w{W}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
    )
