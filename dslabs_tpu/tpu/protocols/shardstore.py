"""Tensorised twin of lab 4's sharded KV store for the search-test
configurations (ShardStorePart1Test.java:test10-12 shape): G groups of ONE
server each, one shard master, one client, a static post-Join config, the
config controller and master timers frozen (tests/test_lab4_shardstore.py
test10-12 mirror these settings from ShardStoreBaseTest.java:209-220).

Why the state collapses so far (all against the object implementations in
dslabs_tpu/labs/shardedstore/shardstore.py and labs/paxos/paxos.py):

* A one-server Paxos group decides synchronously: ``_send_to_all`` delivers
  the leader's own P1a/P2a/P2b locally (paxos.py:238-247), majority = 1, so
  a proposal is chosen, executed, AND garbage-collected inside the original
  handler call (exec -> _leader_exec_update -> maybe_gc clears through the
  executed prefix when n == 1).  The replicated log is therefore always
  empty in every reachable state — no log lanes at all; what remains is the
  decided-slot COUNT (cleared_through/slot_in/executed_through, all equal),
  the heard_from_leader flag (set by the self-delivered P2a, cleared by
  ElectionTimer), and the constant ballot (1, server) from the immediate
  self-election at init (paxos.py:201-205).

* The shard master (PaxosServer with the ShardMaster app, timers frozen)
  logs every FRESH Query — handle_PaxosRequest AMO-wraps read-only
  commands like any other (paxos.py:326-360) — and answers every query
  with the one existing config (shardmaster.py Query: out-of-range or -1
  -> latest).  Its state is (decided count, max executed query seq per
  source); replies are content-constant except the AMO sequence number.

* Client/server query sequence numbers increase on every ``_query_config``
  / QueryTimer (shardstore.py:593-631), so the network's distinct query
  messages are keyed by (source, seq, queried config-num) alone.

Node lanes (node order: 0 = master, 1..G = group servers, G+1 = client):
  master  [mc, mamo_c, mamo_s1..mamo_sG]   decided count + AMO per source
  server g [scfg, samo, scount, sh, sq]    config installed, last executed
                                           client seq, decided count,
                                           heard flag, query seq counter
  client  [k, cfg, cq]                     workload index (W+1 = done),
                                           config known, query seq counter

Message lanes [tag, a, b, c]:
  QRY  [src, seq, cfg_arg]   PaxosRequest(AMOCommand(Query(cfg_arg), src, seq))
                             src: 0 = client, g = server g
  QREP [dst, seq, 0]         PaxosReply(AMOResult(cfg0, seq))
  SSREQ [k, 0, 0]            ShardStoreRequest(AMOCommand(cmd_k, client, k))
  SSREP [k, 0, 0]            ShardStoreReply(AMOResult(result_k, k))
Timer lanes [tag, min, max, p0]: CLIENT(seq) / QUERY / ELECTION / HEARTBEAT.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_shardstore_protocol"]

QRY, QREP, SSREQ, SSREP = 0, 1, 2, 3
T_CLIENT, T_QUERY, T_ELECTION, T_HEARTBEAT = 1, 2, 3, 4

CLIENT_MS = 100     # shardstore.py CLIENT_RETRY_MILLIS
QUERY_MS = 50       # shardstore.py QUERY_MILLIS
ELECTION_MIN, ELECTION_MAX = 150, 300   # paxos.py
HEARTBEAT_MS = 50


def make_shardstore_protocol(groups_of: Sequence[int],
                             net_cap: int = 48,
                             timer_cap: int = 6) -> TensorProtocol:
    """``groups_of[k-1]`` = the group (1-based) owning workload command
    k's key under the static post-Join config — precomputed on the host
    with the same key_to_shard the object servers use."""
    W = len(groups_of)
    G = max(groups_of)
    assert min(groups_of) >= 1
    # Multi-group configs are built by SUCCESSIVE Joins, so the shard
    # master serves configs 0..G-1 and each group walks them with shard
    # handoffs (ShardMove/InstallShards/MoveDone) before reaching the
    # final assignment — that config-walk state machine is not modelled
    # yet; this twin covers the single-group search shape
    # (ShardStorePart1Test.test10).
    assert G == 1, "multi-group twin requires the config-walk model"
    MW, TW = 4, 4
    NW = (2 + G) + 5 * G + 3
    N_NODES = 1 + G + 1
    CLIENT = G + 1

    # lane offsets
    M_MC, M_AMOC, M_AMOS = 0, 1, 2            # master (M_AMOS + g-1)
    SRV = 2 + G                               # server g base: SRV + 5*(g-1)
    C_K, C_CFG, C_CQ = SRV + 5 * G, SRV + 5 * G + 1, SRV + 5 * G + 2

    def srv(g, off):
        return SRV + 5 * (g - 1) + off

    def grp_of(k):
        """Traced workload index -> owning group, via a static where-chain."""
        out = jnp.asarray(groups_of[0], jnp.int32)
        for kk in range(2, W + 1):
            out = jnp.where(k == kk, groups_of[kk - 1], out)
        return out

    def msg_row(cond, tag, a, b=0, c=0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32) for x in (tag, a, b, c)])
        return jnp.where(cond, rec, jnp.full((MW,), SENTINEL, jnp.int32))[None]

    def timer_row(cond, node, tag, mn, mx, p0):
        rec = jnp.stack([jnp.asarray(x, jnp.int32)
                         for x in (node, tag, mn, mx, p0)])
        return jnp.where(cond, rec,
                         jnp.full((1 + TW,), SENTINEL, jnp.int32))[None]

    blank_msg = jnp.full((1, MW), SENTINEL, jnp.int32)
    blank_set = jnp.full((1, 1 + TW), SENTINEL, jnp.int32)

    # ------------------------------------------------------------- handlers

    def step_message(nodes, msg):
        tag, a, b, c = msg[0], msg[1], msg[2], msg[3]
        sends = []
        tsets = []

        # ---- QRY -> master (paxos.py handle_PaxosRequest with the
        # ShardMaster app; n=1: fresh commands decide+execute+GC inline)
        is_qry = tag == QRY
        src, seq = a, b
        # per-source AMO lane (master): client = 0, server g = g
        for sidx in range(0, G + 1):
            lane = M_AMOC if sidx == 0 else M_AMOS + sidx - 1
            here = is_qry & (src == sidx)
            last = nodes[lane]
            fresh = here & (seq > last)
            nodes = nodes.at[lane].set(
                jnp.where(fresh, seq, last).astype(jnp.int32))
            nodes = nodes.at[M_MC].set(
                jnp.where(fresh, nodes[M_MC] + 1,
                          nodes[M_MC]).astype(jnp.int32))
            # reply for fresh or exactly-cached seq (AMO execute: older
            # seqs return None -> no reply)
            sends.append(msg_row(here & (seq >= last), QREP, src, seq))

        # ---- QREP -> client (shardstore.py handle_PaxosReply, client):
        # adopt the config if none, then send the pending command
        is_qrep_c = (tag == QREP) & (a == 0)
        k = nodes[C_K]
        adopt = is_qrep_c & (nodes[C_CFG] == 0)
        nodes = nodes.at[C_CFG].set(
            jnp.where(adopt, 1, nodes[C_CFG]).astype(jnp.int32))
        sends.append(msg_row(adopt & (k <= W), SSREQ, k))

        # ---- QREP -> server g (shardstore.py handle_PaxosReply, server):
        # propose NewConfig iff cfg.config_num == _next_config_num() — the
        # master only ever serves config 0, so only a config-less server
        # matches; deciding it bumps the count and sets heard (self-P2a).
        for g in range(1, G + 1):
            here = (tag == QREP) & (a == g)
            install = here & (nodes[srv(g, 0)] == 0)
            nodes = nodes.at[srv(g, 0)].set(
                jnp.where(install, 1, nodes[srv(g, 0)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, 2)].set(
                jnp.where(install, nodes[srv(g, 2)] + 1,
                          nodes[srv(g, 2)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, 3)].set(
                jnp.where(install, 1, nodes[srv(g, 3)]).astype(jnp.int32))

        # ---- SSREQ -> server grp_of(k) (handle_ShardStoreRequest):
        # ALWAYS proposes (relay-mode chosen entries are not deduped,
        # paxos.py:349-355) -> count+1, heard; executes only with a config
        # (shardstore.py _execute_client_command), AMO-gated.
        is_ss = tag == SSREQ
        kk = a
        kg = grp_of(kk)
        for g in range(1, G + 1):
            here = is_ss & (kg == g)
            nodes = nodes.at[srv(g, 2)].set(
                jnp.where(here, nodes[srv(g, 2)] + 1,
                          nodes[srv(g, 2)]).astype(jnp.int32))
            nodes = nodes.at[srv(g, 3)].set(
                jnp.where(here, 1, nodes[srv(g, 3)]).astype(jnp.int32))
            has_cfg = nodes[srv(g, 0)] == 1
            samo = nodes[srv(g, 1)]
            execd = here & has_cfg & (kk > samo)
            nodes = nodes.at[srv(g, 1)].set(
                jnp.where(execd, kk, samo).astype(jnp.int32))
            sends.append(msg_row(here & has_cfg & (kk >= samo), SSREP, kk))

        # ---- SSREP -> client (ClientWorker pumps the next command inside
        # the reply handler; _send_pending needs the config we must have)
        is_rep = tag == SSREP
        match = is_rep & (a == k) & (k <= W)
        k2 = jnp.where(match, k + 1, k)
        nodes = nodes.at[C_K].set(k2.astype(jnp.int32))
        has_next = match & (k2 <= W)
        sends.append(msg_row(has_next, SSREQ, k2))
        tsets.append(timer_row(has_next, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k2))

        sends = jnp.concatenate(sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        sends = []
        tsets = []

        # ---- ClientTimer (shardstore.py on_ClientTimer): re-query (+1
        # more query when there is no config yet — _send_pending falls back
        # to _query_config) and re-send the pending command.
        k = nodes[C_K]
        live = ((node_idx == CLIENT) & (tag == T_CLIENT) & (p0 == k)
                & (k <= W))
        cq = nodes[C_CQ]
        has_cfg = nodes[C_CFG] == 1
        cq2 = jnp.where(live, jnp.where(has_cfg, cq + 1, cq + 2), cq)
        nodes = nodes.at[C_CQ].set(cq2.astype(jnp.int32))
        sends.append(msg_row(live, QRY, 0, cq + 1, -1))
        sends.append(jnp.where(has_cfg,
                               msg_row(live, SSREQ, k)[0],
                               msg_row(live, QRY, 0, cq + 2, -1)[0])[None])
        tsets.append(timer_row(live, CLIENT, T_CLIENT,
                               CLIENT_MS, CLIENT_MS, k))

        for g in range(1, G + 1):
            here = node_idx == g
            # ---- QueryTimer (shardstore.py on_QueryTimer): leader always,
            # reconfig always done -> fresh query for the next config num.
            is_q = here & (tag == T_QUERY)
            sq = nodes[srv(g, 4)]
            nodes = nodes.at[srv(g, 4)].set(
                jnp.where(is_q, sq + 1, sq).astype(jnp.int32))
            sends.append(msg_row(is_q, QRY, g, sq + 1, nodes[srv(g, 0)]))
            tsets.append(timer_row(is_q, g, T_QUERY, QUERY_MS, QUERY_MS, 0))

            # ---- ElectionTimer (paxos.py on_ElectionTimer): the lone
            # server is its own decided leader; only heard resets.
            is_el = here & (tag == T_ELECTION)
            nodes = nodes.at[srv(g, 3)].set(
                jnp.where(is_el, 0, nodes[srv(g, 3)]).astype(jnp.int32))
            tsets.append(timer_row(is_el, g, T_ELECTION,
                                   ELECTION_MIN, ELECTION_MAX, 0))

            # ---- HeartbeatTimer: no peers, nothing in flight — pure
            # re-arm (state unchanged).
            is_hb = here & (tag == T_HEARTBEAT)
            tsets.append(timer_row(is_hb, g, T_HEARTBEAT,
                                   HEARTBEAT_MS, HEARTBEAT_MS, 0))

        sends = jnp.concatenate(sends + [blank_msg] * (MAX_SENDS - len(sends)))
        tsets = jnp.concatenate(tsets + [blank_set] * (MAX_SETS - len(tsets)))
        return nodes, sends[:MAX_SENDS], tsets[:MAX_SETS]

    # Row budgets = the TOTAL rows each step function appends (rows are
    # individually condition-masked; the pad/slice below must never
    # truncate a real row).  step_message: (G+1) QREP + 1 client SSREQ +
    # G SSREP + 1 pumped SSREQ; step_timer: 2 client + G query sends.
    MAX_SENDS = 2 * G + 3
    MAX_SETS = 1 + 3 * G        # client CT + per-server query/election/hb

    # ------------------------------------------------------------- initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        nodes[M_MC] = 1          # the staged Join is decided slot 1
        nodes[C_K] = 1           # PUT(1) pending
        # init() queries once; send_command -> _send_pending with no
        # config falls back to _query_config and queries AGAIN
        # (shardstore.py:624-650), so two queries are already in flight.
        nodes[C_CQ] = 2
        return nodes

    def init_messages():
        return np.array([[QRY, 0, 1, -1], [QRY, 0, 2, -1]], np.int32)

    def init_timers():
        rows = []
        for g in range(1, G + 1):
            # ShardStoreServer.init: paxos.init (Election, then the
            # immediate self-election arms Heartbeat), then QueryTimer.
            rows.append([g, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
            rows.append([g, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, 0])
            rows.append([g, T_QUERY, QUERY_MS, QUERY_MS, 0])
        rows.append([CLIENT, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(rows, np.int32)

    def msg_dest(msg):
        tag, a = msg[0], msg[1]
        dest = jnp.asarray(0, jnp.int32)                      # QRY -> master
        dest = jnp.where(tag == QREP,
                         jnp.where(a == 0, CLIENT, a), dest)
        dest = jnp.where(tag == SSREQ, grp_of(msg[1]), dest)
        dest = jnp.where(tag == SSREP, CLIENT, dest)
        return dest

    def clients_done(state):
        return state["nodes"][C_K] == W + 1

    return TensorProtocol(
        name=f"shardstore-g{G}-w{W}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
    )
