"""Tensorised twin of lab 3 multi-Paxos — the north-star bench workload
(BASELINE.json: lab3-paxos BFS states/min).

Mirrors the object implementation in dslabs_tpu/labs/paxos/paxos.py
handler-for-handler, including everything that participates in object state
equality: the log, ballot/leader/heard flags, raw P1b vote contents,
P2b vote bitmasks, proposed_seq, peer_executed + GC frontiers, and the AMO
application state.  Handler cascades (leader self-accept/self-vote on
P2a/P2b, execution chains with client replies) are inlined exactly as the
object's local ``deliver_message`` calls are.

Workload model: ``n_clients`` clients each Put their own key W times
(value = f(seq)), so the KVStore + AMO state collapses to one
last-executed-seq lane per client.  Command ids: ``c * W + s`` (1-based);
0 = no-op.

Packed lanes per server (offsets from the server's base):
  0 ballot (round * n + leader_idx)   4 executed_through
  1 leader flag                       5 cleared_through
  2 heard_from_leader                 6 gc_through
  3 slot_in                           7 peer_executed bitmask
  8..8+n-1      peer_executed values
  AMO           n_clients lanes: last executed seq per client
  PROP          n_clients lanes: proposed_seq (0 = none)
  P2B           S lanes: vote bitmask per slot
  LOG           S x [exists, ballot, cmd, chosen]
  VOTES         n x [have, S x [exists, ballot, cmd, chosen]]  raw P1b votes

Clients contribute one lane each: k = seq in flight (W+1 = done).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_paxos_protocol"]

# Message tags
REQ, P1A, P1B, P2A, P2B, HB, HBR, CREQ, CREP, REPLY = range(10)
# Timer tags
T_ELECTION, T_HEARTBEAT, T_CLIENT = 1, 2, 3

ELECTION_MIN, ELECTION_MAX = 150, 300
HEARTBEAT_MS = 50
CLIENT_MS = 100


def make_paxos_protocol(n: int = 3, n_clients: int = 1, w: int = 1,
                        max_slots: int = 2, net_cap: int = 64,
                        timer_cap: int = 8) -> TensorProtocol:
    S = max_slots
    NC = n_clients
    maj = n // 2 + 1

    # ---- server lane offsets
    PEER = 8
    AMO = PEER + n
    PROP = AMO + NC
    P2BV = PROP + NC
    LOG = P2BV + S
    VOTES = LOG + 4 * S
    SW = VOTES + n * (1 + 4 * S)
    NW = n * SW + NC                       # + one k lane per client
    N_NODES = n + NC

    # ---- message layout: [tag, frm, to, p0..]  payload:
    #   REQ:   [client, seq]
    #   P1A:   [ballot]
    #   P1B:   [ballot, S x (exists, lballot, cmd, chosen)]
    #   P2A:   [ballot, slot, cmd]
    #   P2B:   [ballot, slot]
    #   HB:    [ballot, commit, gc]     HBR: [ballot, executed]
    #   CREQ:  [from_slot]              CREP: [base, count, S x cmd]
    #   REPLY: [client, seq]
    PAYLOAD = max(1 + 4 * S, 3, 2 + S)
    MW = 3 + PAYLOAD
    TW = 4  # [tag, min, max, p0]
    MAX_SENDS = 64 + n   # SRV_SENDS + CLI_SENDS (finalize() asserts fit)
    MAX_SETS = 4 + 1

    def cmd_id(client, seq):
        return client * w + seq  # 1-based; 0 = none/noop

    def cmd_client(cmd):
        return (cmd - 1) // w

    def cmd_seq(cmd):
        return (cmd - 1) % w + 1

    # ------------------------------------------------------------- builders

    def mk_msg(tag, frm, to, payload):
        lanes = [jnp.asarray(tag, jnp.int32), jnp.asarray(frm, jnp.int32),
                 jnp.asarray(to, jnp.int32)]
        for v in payload:
            lanes.append(jnp.asarray(v, jnp.int32))
        while len(lanes) < MW:
            lanes.append(jnp.zeros((), jnp.int32))
        return jnp.stack(lanes)

    class Sends:
        """Collects conditional sends; blank rows are all-SENTINEL so blocks
        from mutually exclusive branches merge by elementwise minimum."""

        def __init__(self):
            self.rows = []

        def add(self, cond, tag, frm, to, payload):
            rec = mk_msg(tag, frm, to, payload)
            blank = jnp.full((MW,), SENTINEL, jnp.int32)
            self.rows.append(jnp.where(cond, rec, blank))

        def finalize(self, count):
            rows = list(self.rows)
            assert len(rows) <= count, (len(rows), count)
            blank = jnp.full((MW,), SENTINEL, jnp.int32)
            while len(rows) < count:
                rows.append(blank)
            return jnp.stack(rows)

    class Sets:
        def __init__(self):
            self.rows = []

        def add(self, cond, node, tag, mn, mx, p0):
            rec = jnp.stack([
                jnp.asarray(node, jnp.int32), jnp.asarray(tag, jnp.int32),
                jnp.asarray(mn, jnp.int32), jnp.asarray(mx, jnp.int32),
                jnp.asarray(p0, jnp.int32)])
            blank = jnp.full((1 + TW,), SENTINEL, jnp.int32)
            self.rows.append(jnp.where(cond, rec, blank))

        def finalize(self, count):
            rows = list(self.rows)
            assert len(rows) <= count, (len(rows), count)
            blank = jnp.full((1 + TW,), SENTINEL, jnp.int32)
            while len(rows) < count:
                rows.append(blank)
            return jnp.stack(rows)

    # ----------------------------------------------------- server accessors

    def sbase(i):
        return i * SW

    def get(nodes, i, off):
        return nodes[sbase(i) + off]

    def setv(nodes, i, off, val):
        return nodes.at[sbase(i) + off].set(jnp.asarray(val, jnp.int32))

    def log_get(nodes, i, slot):
        """slot is 1-based traced int; returns (exists, ballot, cmd, chosen)
        with slot clamped into range (callers mask)."""
        s0 = sbase(i) + LOG + 4 * (slot - 1).clip(0, S - 1)
        return (jax.lax.dynamic_slice(nodes, (s0,), (4,)))

    def log_set(nodes, i, slot, entry, cond):
        s0 = sbase(i) + LOG + 4 * (slot - 1).clip(0, S - 1)
        in_range = (slot >= 1) & (slot <= S) & cond
        cur = jax.lax.dynamic_slice(nodes, (s0,), (4,))
        new = jnp.where(in_range, jnp.asarray(entry, jnp.int32), cur)
        return jax.lax.dynamic_update_slice(nodes, new, (s0,))

    def exec_chain(nodes, i, sends: Sends, cond):
        """Execute contiguous chosen slots (paxos.py _execute_chosen),
        sending client replies; leader updates its own peer_executed."""
        for _ in range(S):
            ex = get(nodes, i, 4)
            e = log_get(nodes, i, ex + 1)
            can = cond & (ex + 1 <= S) & (e[0] == 1) & (e[3] == 1)
            nodes = setv(nodes, i, 4, jnp.where(can, ex + 1, ex))
            cmd = e[2]
            has_cmd = can & (cmd != 0)
            cl = cmd_client(cmd).clip(0, NC - 1)
            sq = cmd_seq(cmd)
            last = jax.lax.dynamic_index_in_dim(
                nodes, sbase(i) + AMO + cl, keepdims=False)
            reply = has_cmd & (sq >= last)
            newlast = jnp.where(has_cmd & (sq > last), sq, last)
            nodes = jax.lax.dynamic_update_index_in_dim(
                nodes, newlast.astype(jnp.int32), sbase(i) + AMO + cl, 0)
            sends.add(reply, REPLY, i, n + cl, [cl, sq])
        # Leader bookkeeping + GC (object: peer_executed[self]=exec; gc)
        is_leader = (cond & (get(nodes, i, 1) == 1)
                     & (get(nodes, i, 0) % n == i))
        return _leader_exec_update(nodes, i, is_leader)

    def _leader_exec_update(nodes, i, is_leader):
        ex = get(nodes, i, 4)
        mask = get(nodes, i, 7)
        nodes = setv(nodes, i, 7,
                     jnp.where(is_leader, mask | (1 << i), mask))
        cur = get(nodes, i, PEER + i)
        nodes = setv(nodes, i, PEER + i, jnp.where(is_leader, ex, cur))
        return maybe_gc(nodes, i, is_leader)

    def maybe_gc(nodes, i, cond):
        mask = get(nodes, i, 7)
        have_all = mask == (1 << n) - 1
        floor = get(nodes, i, PEER + 0)
        for j in range(1, n):
            floor = jnp.minimum(floor, get(nodes, i, PEER + j))
        do = cond & have_all & (floor > get(nodes, i, 6))
        nodes = setv(nodes, i, 6,
                     jnp.where(do, floor, get(nodes, i, 6)))
        return gc_to(nodes, i, floor, do)

    def gc_to(nodes, i, through, cond):
        through = jnp.minimum(through, get(nodes, i, 4))
        cleared = get(nodes, i, 5)
        do = cond & (through > cleared)
        for slot in range(1, S + 1):
            clear = do & (jnp.asarray(slot) > cleared) & (jnp.asarray(slot) <= through)
            nodes = log_set(nodes, i, jnp.asarray(slot), [0, 0, 0, 0], clear)
        nodes = setv(nodes, i, 5, jnp.where(do, through, cleared))
        return nodes

    def accept_p2a(nodes, i, ballot, slot, cmd, cond):
        """The acceptor body of handle_P2a (ballot already >= checked)."""
        e = log_get(nodes, i, slot)
        write = cond & (slot > get(nodes, i, 5)) & ~((e[0] == 1) & (e[3] == 1))
        return log_set(nodes, i, slot, [1, ballot, cmd, 0], write)

    def record_own_p2b(nodes, i, ballot, slot, cond):
        """Leader self-vote (send_p2a -> self P2a -> self P2b), which can
        never reach majority alone for n >= 2 (no cascade)."""
        e = log_get(nodes, i, slot)
        ok = (cond & (get(nodes, i, 0) == ballot)
              & (e[0] == 1) & (e[3] == 0) & (e[1] == ballot))
        off = sbase(i) + P2BV + (slot - 1).clip(0, S - 1)
        cur = jax.lax.dynamic_index_in_dim(nodes, off, keepdims=False)
        return jax.lax.dynamic_update_index_in_dim(
            nodes, jnp.where(ok, cur | (1 << i), cur).astype(jnp.int32),
            off, 0)

    def send_p2a(nodes, i, slot, sends: Sends, cond):
        """Broadcast P2a for log[slot] + inline self-accept/self-vote."""
        e = log_get(nodes, i, slot)
        ballot = get(nodes, i, 0)
        for j in range(n):
            if j == i:
                continue
            sends.add(cond, P2A, i, j, [ballot, slot, e[2]])
        nodes = accept_p2a(nodes, i, ballot, slot, e[2], cond)
        nodes = setv(nodes, i, 2, jnp.where(cond, 1, get(nodes, i, 2)))
        nodes = record_own_p2b(nodes, i, ballot, slot, cond)
        return nodes

    def heartbeat_sends(nodes, i, sends: Sends, cond):
        ballot = get(nodes, i, 0)
        commit = get(nodes, i, 4)
        gc = get(nodes, i, 6)
        for j in range(n):
            if j == i:
                continue
            sends.add(cond, HB, i, j, [ballot, commit, gc])

    # ----------------------------------------------------- message handlers

    # Row budgets per handler block (static add-counts; asserted in
    # finalize).  Branch blocks are mutually exclusive, so they share rows.
    SRV_SENDS, SRV_SETS = 64, 4
    CLI_SENDS, CLI_SETS = n, 1

    def step_message(nodes, msg):
        tag, frm, to = msg[0], msg[1], msg[2]
        p = msg[3:]
        out = nodes
        srv_rows, srv_sets = None, None
        for i in range(n):
            here = to == i
            sends, sets = Sends(), Sets()
            out = _server_handle(out, i, here, tag, frm, p, sends, sets)
            r, t = sends.finalize(SRV_SENDS), sets.finalize(SRV_SETS)
            srv_rows = r if srv_rows is None else jnp.minimum(srv_rows, r)
            srv_sets = t if srv_sets is None else jnp.minimum(srv_sets, t)
        cli_rows, cli_sets = None, None
        for c in range(NC):
            here = to == n + c
            sends, sets = Sends(), Sets()
            out = _client_handle(out, c, here, tag, p, sends, sets)
            r, t = sends.finalize(CLI_SENDS), sets.finalize(CLI_SETS)
            cli_rows = r if cli_rows is None else jnp.minimum(cli_rows, r)
            cli_sets = t if cli_sets is None else jnp.minimum(cli_sets, t)
        rows = jnp.concatenate([srv_rows, cli_rows])
        tsets = jnp.concatenate([srv_sets, cli_sets])
        return out, rows, tsets

    def _server_handle(nodes, i, here, tag, frm, p, sends, sets):
        ballot = get(nodes, i, 0)

        # ---- PaxosRequest (handle_PaxosRequest, paxos.py)
        is_req = here & (tag == REQ)
        client, seq = p[0], p[1]
        amo_last = jax.lax.dynamic_index_in_dim(
            nodes, sbase(i) + AMO + client.clip(0, NC - 1), keepdims=False)
        already = seq <= amo_last
        sends.add(is_req & already & (seq == amo_last), REPLY, i,
                  n + client, [client, seq])
        is_leader = (get(nodes, i, 1) == 1) & (ballot % n == i)
        believed = ballot % n
        fwd = (is_req & ~already & ~is_leader
               & ((frm == i) | (frm >= n)) & (believed != i))
        sends.add(fwd, REQ, i, believed, [client, seq])
        prop = jax.lax.dynamic_index_in_dim(
            nodes, sbase(i) + PROP + client.clip(0, NC - 1), keepdims=False)
        do_prop = is_req & ~already & is_leader & (seq > prop)
        slot = get(nodes, i, 3)
        in_range = slot <= S
        do_prop = do_prop & in_range
        nodes = jax.lax.dynamic_update_index_in_dim(
            nodes, jnp.where(do_prop, seq, prop).astype(jnp.int32),
            sbase(i) + PROP + client.clip(0, NC - 1), 0)
        nodes = setv(nodes, i, 3, jnp.where(do_prop, slot + 1, slot))
        nodes = log_set(nodes, i, slot,
                        [1, ballot, cmd_id(client, seq), 0], do_prop)
        nodes = send_p2a(nodes, i, slot, sends, do_prop)

        # ---- P1a (handle_P1a)
        is_p1a = here & (tag == P1A)
        mb = p[0]
        adopt = is_p1a & (mb > ballot)
        nodes = setv(nodes, i, 0, jnp.where(adopt, mb, get(nodes, i, 0)))
        nodes = setv(nodes, i, 1, jnp.where(adopt, 0, get(nodes, i, 1)))
        promise = is_p1a & (mb == get(nodes, i, 0))
        log_flat = jax.lax.dynamic_slice(nodes, (sbase(i) + LOG,), (4 * S,))
        sends.add(promise, P1B, i, frm,
                  [get(nodes, i, 0)] + [log_flat[j] for j in range(4 * S)])

        # ---- P1b (handle_P1b)
        is_p1b = here & (tag == P1B)
        vb = p[0]
        accept_vote = (is_p1b & (vb == get(nodes, i, 0))
                       & (get(nodes, i, 0) % n == i)
                       & (get(nodes, i, 1) == 0))
        voff = sbase(i) + VOTES + frm.clip(0, n - 1) * (1 + 4 * S)
        vrec = jnp.concatenate([jnp.ones((1,), jnp.int32),
                                p[1:1 + 4 * S].astype(jnp.int32)])
        cur_v = jax.lax.dynamic_slice(nodes, (voff,), (1 + 4 * S,))
        nodes = jax.lax.dynamic_update_slice(
            nodes, jnp.where(accept_vote, vrec, cur_v), (voff,))
        nvotes = jnp.zeros((), jnp.int32)
        for j in range(n):
            nvotes = nvotes + get(nodes, i, VOTES + j * (1 + 4 * S))
        win = accept_vote & (nvotes >= maj)
        nodes = _p1b_win(nodes, i, win, sends, sets)

        # ---- P2a (handle_P2a)
        is_p2a = here & (tag == P2A)
        ab, aslot, acmd = p[0], p[1], p[2]
        ok2a = is_p2a & (ab >= get(nodes, i, 0))
        nodes = setv(nodes, i, 1,
                     jnp.where(ok2a & (ab > get(nodes, i, 0)), 0,
                               get(nodes, i, 1)))
        nodes = setv(nodes, i, 0, jnp.where(ok2a, ab, get(nodes, i, 0)))
        nodes = setv(nodes, i, 2, jnp.where(ok2a, 1, get(nodes, i, 2)))
        nodes = accept_p2a(nodes, i, ab, aslot, acmd, ok2a)
        sends.add(ok2a, P2B, i, frm, [ab, aslot])

        # ---- P2b (handle_P2b)
        is_p2b = here & (tag == P2B)
        bb, bslot = p[0], p[1]
        lead_ok = (is_p2b & (bb == get(nodes, i, 0))
                   & (get(nodes, i, 1) == 1) & (get(nodes, i, 0) % n == i))
        e = log_get(nodes, i, bslot)
        count_ok = lead_ok & (e[0] == 1) & (e[3] == 0) & (e[1] == bb)
        p2off = sbase(i) + P2BV + (bslot - 1).clip(0, S - 1)
        vmask = jax.lax.dynamic_index_in_dim(nodes, p2off, keepdims=False)
        vmask2 = jnp.where(count_ok, vmask | (1 << frm.clip(0, n - 1)), vmask)
        chosen_now = count_ok & (_popcount(vmask2) >= maj)
        nodes = jax.lax.dynamic_update_index_in_dim(
            nodes, jnp.where(chosen_now, 0, vmask2).astype(jnp.int32),
            p2off, 0)
        nodes = log_set(nodes, i, bslot, [1, e[1], e[2], 1], chosen_now)
        nodes = _maybe_exec(nodes, i, chosen_now, sends)

        # ---- Heartbeat (handle_Heartbeat)
        is_hb = here & (tag == HB)
        hb_b, hb_commit, hb_gc = p[0], p[1], p[2]
        hb_ok = is_hb & (hb_b >= get(nodes, i, 0))
        nodes = setv(nodes, i, 1,
                     jnp.where(hb_ok & (hb_b > get(nodes, i, 0)), 0,
                               get(nodes, i, 1)))
        nodes = setv(nodes, i, 0, jnp.where(hb_ok, hb_b, get(nodes, i, 0)))
        nodes = setv(nodes, i, 2, jnp.where(hb_ok, 1, get(nodes, i, 2)))
        nodes = gc_to(nodes, i, hb_gc, hb_ok)
        lagging = hb_ok & (get(nodes, i, 4) < hb_commit)
        sends.add(lagging, CREQ, i, frm, [get(nodes, i, 4) + 1])
        sends.add(hb_ok, HBR, i, frm, [get(nodes, i, 0), get(nodes, i, 4)])

        # ---- HeartbeatReply (handle_HeartbeatReply)
        is_hbr = here & (tag == HBR)
        rb, rexec = p[0], p[1]
        hbr_ok = (is_hbr & (rb == get(nodes, i, 0))
                  & (get(nodes, i, 1) == 1) & (get(nodes, i, 0) % n == i))
        poff = sbase(i) + PEER + frm.clip(0, n - 1)
        pcur = jax.lax.dynamic_index_in_dim(nodes, poff, keepdims=False)
        nodes = jax.lax.dynamic_update_index_in_dim(
            nodes, jnp.where(hbr_ok, jnp.maximum(pcur, rexec),
                             pcur).astype(jnp.int32), poff, 0)
        mask = get(nodes, i, 7)
        nodes = setv(nodes, i, 7,
                     jnp.where(hbr_ok, mask | (1 << frm.clip(0, n - 1)),
                               mask))
        nodes = maybe_gc(nodes, i, hbr_ok)

        # ---- CatchupRequest (handle_CatchupRequest)
        is_cq = here & (tag == CREQ)
        from_slot = jnp.maximum(p[0], get(nodes, i, 5) + 1)
        cmds = []
        count = jnp.zeros((), jnp.int32)
        contiguous = jnp.asarray(True)
        for k in range(S):
            slot = from_slot + k
            e = log_get(nodes, i, slot)
            ok = (contiguous & (slot <= get(nodes, i, 4))
                  & (e[0] == 1) & (e[3] == 1))
            contiguous = ok
            cmds.append(jnp.where(ok, e[2], 0))
            count = count + ok.astype(jnp.int32)
        sends.add(is_cq & (count > 0), CREP, i, frm,
                  [from_slot, count] + cmds)

        # ---- CatchupReply (handle_CatchupReply)
        is_cp = here & (tag == CREP)
        base, ccount = p[0], p[1]
        for k in range(S):
            slot = base + k
            cmd = p[2 + k]
            e = log_get(nodes, i, slot)
            install = (is_cp & (jnp.asarray(k) < ccount)
                       & (slot > get(nodes, i, 5))
                       & ~((e[0] == 1) & (e[3] == 1)))
            nodes = log_set(nodes, i, slot,
                            [1, get(nodes, i, 0), cmd, 1], install)
        nodes = _maybe_exec(nodes, i, is_cp, sends)
        return nodes

    def _maybe_exec(nodes, i, cond, sends):
        return exec_chain(nodes, i, sends, cond)

    def _p1b_win(nodes, i, win, sends: Sends, sets: Sets):
        """Phase-1 victory (handle_P1b body after majority)."""
        ballot = get(nodes, i, 0)
        nodes = setv(nodes, i, 1, jnp.where(win, 1, get(nodes, i, 1)))
        # p2b_votes = {}; peer_executed = {self: exec}
        for s in range(S):
            nodes = setv(nodes, i, P2BV + s,
                         jnp.where(win, 0, get(nodes, i, P2BV + s)))
        nodes = setv(nodes, i, 7,
                     jnp.where(win, 1 << i, get(nodes, i, 7)))
        for j in range(n):
            nodes = setv(nodes, i, PEER + j,
                         jnp.where(win & (jnp.asarray(j) == i),
                                   get(nodes, i, 4),
                                   jnp.where(win, 0, get(nodes, i, PEER + j))))
        # Adoption: per slot, chosen wins; else max-ballot accepted.
        for s in range(1, S + 1):
            a_ex = jnp.zeros((), jnp.int32)
            a_b = jnp.full((), -1, jnp.int32)
            a_c = jnp.zeros((), jnp.int32)
            a_ch = jnp.zeros((), jnp.int32)
            for j in range(n):
                vo = sbase(i) + VOTES + j * (1 + 4 * S)
                have = nodes[vo]
                ex = nodes[vo + 1 + 4 * (s - 1) + 0]
                vb = nodes[vo + 1 + 4 * (s - 1) + 1]
                vc = nodes[vo + 1 + 4 * (s - 1) + 2]
                vch = nodes[vo + 1 + 4 * (s - 1) + 3]
                valid = (have == 1) & (ex == 1)
                take = valid & ((vch == 1) & (a_ch == 0)
                                | (a_ch == 0) & ((a_ex == 0) | (vb > a_b)))
                a_b = jnp.where(take, vb, a_b)
                a_c = jnp.where(take, vc, a_c)
                a_ch = jnp.where(take, jnp.maximum(a_ch, vch), a_ch)
                a_ex = jnp.where(take, 1, a_ex)
            mine = log_get(nodes, i, jnp.asarray(s))
            adopt = win & (a_ex == 1) & (jnp.asarray(s) > get(nodes, i, 5)) \
                & ~((mine[0] == 1) & (mine[3] == 1))
            nodes = log_set(nodes, i, jnp.asarray(s),
                            [1, ballot, a_c, a_ch], adopt)
        # top = last non-empty; fill holes with no-ops; repropose unchosen.
        top = get(nodes, i, 5)
        for s in range(1, S + 1):
            e = log_get(nodes, i, jnp.asarray(s))
            top = jnp.where(e[0] == 1, jnp.asarray(s, jnp.int32), top)
        for s in range(1, S + 1):
            e = log_get(nodes, i, jnp.asarray(s))
            in_span = win & (jnp.asarray(s) > get(nodes, i, 4)) & (jnp.asarray(s) <= top)
            fill = in_span & (e[0] == 0)
            nodes = log_set(nodes, i, jnp.asarray(s), [1, ballot, 0, 0], fill)
            e2 = log_get(nodes, i, jnp.asarray(s))
            reprop = in_span & (e2[3] == 0)
            nodes = send_p2a(nodes, i, jnp.asarray(s, jnp.int32), sends, reprop)
        nodes = setv(nodes, i, 3, jnp.where(win, top + 1, get(nodes, i, 3)))
        # proposed_seq from logged commands (max seq per client).
        for c in range(NC):
            best = jnp.zeros((), jnp.int32)
            for s in range(1, S + 1):
                e = log_get(nodes, i, jnp.asarray(s))
                mine_c = (e[0] == 1) & (e[2] != 0) & (cmd_client(e[2]) == c)
                best = jnp.where(mine_c, jnp.maximum(best, cmd_seq(e[2])), best)
            nodes = setv(nodes, i, PROP + c,
                         jnp.where(win, best, get(nodes, i, PROP + c)))
        nodes = _maybe_exec(nodes, i, win, sends)
        sets.add(win, i, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, ballot)
        heartbeat_sends(nodes, i, sends, win)
        return nodes

    def _client_handle(nodes, c, here, tag, p, sends: Sends, sets: Sets):
        koff = n * SW + c
        k = nodes[koff]
        is_reply = here & (tag == REPLY) & (p[0] == c)
        match = is_reply & (p[1] == k) & (k <= w)
        k2 = jnp.where(match, k + 1, k)
        nodes = nodes.at[koff].set(k2)
        has_next = match & (k2 <= w)
        for j in range(n):
            sends.add(has_next, REQ, n + c, j, [jnp.asarray(c), k2])
        sets.add(has_next, n + c, T_CLIENT, CLIENT_MS, CLIENT_MS, k2)
        return nodes

    # ------------------------------------------------------- timer handlers

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        out = nodes
        srv_rows, srv_sets = None, None
        for i in range(n):
            here = node_idx == i
            sends, sets = Sends(), Sets()
            out = _server_timer(out, i, here, tag, p0, sends, sets)
            r, t = sends.finalize(SRV_SENDS), sets.finalize(SRV_SETS)
            srv_rows = r if srv_rows is None else jnp.minimum(srv_rows, r)
            srv_sets = t if srv_sets is None else jnp.minimum(srv_sets, t)
        cli_rows, cli_sets = None, None
        for c in range(NC):
            here = node_idx == n + c
            sends, sets = Sends(), Sets()
            koff = n * SW + c
            k = out[koff]
            live = here & (tag == T_CLIENT) & (p0 == k) & (k <= w)
            for j in range(n):
                sends.add(live, REQ, n + c, j, [jnp.asarray(c), k])
            sets.add(live, n + c, T_CLIENT, CLIENT_MS, CLIENT_MS, k)
            r, t = sends.finalize(CLI_SENDS), sets.finalize(CLI_SETS)
            cli_rows = r if cli_rows is None else jnp.minimum(cli_rows, r)
            cli_sets = t if cli_sets is None else jnp.minimum(cli_sets, t)
        rows = jnp.concatenate([srv_rows, cli_rows])
        tsets = jnp.concatenate([srv_sets, cli_sets])
        return out, rows, tsets

    def _server_timer(nodes, i, here, tag, p0, sends: Sends, sets: Sets):
        ballot = get(nodes, i, 0)
        is_leader = (get(nodes, i, 1) == 1) & (ballot % n == i)

        # ---- ElectionTimer (on_ElectionTimer + _start_election inline)
        is_el = here & (tag == T_ELECTION)
        elect = is_el & ~is_leader & (get(nodes, i, 2) == 0)
        new_ballot = (ballot // n + 1) * n + i
        nodes = setv(nodes, i, 0, jnp.where(elect, new_ballot, get(nodes, i, 0)))
        nodes = setv(nodes, i, 1, jnp.where(elect, 0, get(nodes, i, 1)))
        for j in range(n):
            vo = sbase(i) + VOTES + j * (1 + 4 * S)
            cur = jax.lax.dynamic_slice(nodes, (vo,), (1 + 4 * S,))
            nodes = jax.lax.dynamic_update_slice(
                nodes, jnp.where(elect, jnp.zeros_like(cur), cur), (vo,))
        for j in range(n):
            if j == i:
                continue
            sends.add(elect, P1A, i, j, [new_ballot])
        # Self-promise: own vote with own log (P1a -> P1b self-delivery).
        log_flat = jax.lax.dynamic_slice(nodes, (sbase(i) + LOG,), (4 * S,))
        vo = sbase(i) + VOTES + i * (1 + 4 * S)
        own = jnp.concatenate([jnp.ones((1,), jnp.int32), log_flat])
        cur = jax.lax.dynamic_slice(nodes, (vo,), (1 + 4 * S,))
        nodes = jax.lax.dynamic_update_slice(
            nodes, jnp.where(elect, own, cur), (vo,))
        # (majority with one vote only when n == 1 — not modelled here)
        nodes = setv(nodes, i, 2, jnp.where(is_el, 0, get(nodes, i, 2)))
        sets.add(is_el, i, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0)

        # ---- HeartbeatTimer (on_HeartbeatTimer)
        is_hbt = here & (tag == T_HEARTBEAT)
        live = is_hbt & (p0 == get(nodes, i, 0)) & is_leader
        heartbeat_sends(nodes, i, sends, live)
        for s in range(1, S + 1):
            e = log_get(nodes, i, jnp.asarray(s))
            inflight = (live & (jnp.asarray(s) > get(nodes, i, 4))
                        & (jnp.asarray(s) < get(nodes, i, 3))
                        & (e[0] == 1) & (e[3] == 0))
            nodes = send_p2a(nodes, i, jnp.asarray(s, jnp.int32), sends,
                             inflight)
        sets.add(live, i, T_HEARTBEAT, HEARTBEAT_MS, HEARTBEAT_MS, p0)
        return nodes

    # ------------------------------------------------------------ initials

    def init_nodes():
        nodes = np.zeros((NW,), np.int32)
        for i in range(n):
            nodes[sbase(i) + 3] = 1  # slot_in = 1
        for c in range(NC):
            nodes[n * SW + c] = 1    # first command in flight
        return nodes

    def init_messages():
        msgs = []
        for c in range(NC):
            for j in range(n):
                rec = np.zeros((MW,), np.int32)
                rec[0:3] = [REQ, n + c, j]
                rec[3:5] = [c, 1]
                msgs.append(rec)
        return np.stack(msgs)

    def init_timers():
        recs = []
        for i in range(n):
            recs.append([i, T_ELECTION, ELECTION_MIN, ELECTION_MAX, 0])
        for c in range(NC):
            recs.append([n + c, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(recs, np.int32)

    def msg_dest(msg):
        return msg[2]

    # ----------------------------------------------------------- predicates

    def clients_done(state):
        done = jnp.asarray(True)
        for c in range(NC):
            done = done & (state["nodes"][n * SW + c] == w + 1)
        return done

    def none_decided(state):
        nd = jnp.asarray(True)
        for c in range(NC):
            nd = nd & (state["nodes"][n * SW + c] == 1)
        return nd

    def logs_consistent(state):
        """slotValid core: no two different commands chosen in a slot."""
        ok = jnp.asarray(True)
        nodes = state["nodes"]
        for s in range(1, S + 1):
            chosen_cmd = jnp.full((), -1, jnp.int32)
            seen = jnp.zeros((), jnp.int32)
            bad = jnp.asarray(False)
            for i in range(n):
                e0 = nodes[sbase(i) + LOG + 4 * (s - 1)]
                ech = nodes[sbase(i) + LOG + 4 * (s - 1) + 3]
                ec = nodes[sbase(i) + LOG + 4 * (s - 1) + 2]
                is_ch = (e0 == 1) & (ech == 1)
                bad = bad | (is_ch & (seen == 1) & (ec != chosen_cmd))
                chosen_cmd = jnp.where(is_ch, ec, chosen_cmd)
                seen = jnp.where(is_ch, 1, seen)
            ok = ok & ~bad
        return ok

    return TensorProtocol(
        name=f"paxos-n{n}-c{NC}-w{w}-s{S}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        invariants={"LOGS_CONSISTENT": logs_consistent},
        goals={"CLIENTS_DONE": clients_done},
    )


def _popcount(x):
    x = x.astype(jnp.uint32)
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    return ((x * 0x01010101) >> 24).astype(jnp.int32)
