"""Tensorised twin of lab 2 primary-backup (ViewServer + PBServer/PBClient).

Mirrors the object implementation handler-for-handler
(dslabs_tpu/labs/primarybackup/viewserver.py, pb.py; reference spec
PrimaryBackupTest.java:75-905, ViewServerTest.java:40-303), including the
pieces that make the search graph what it is: the ViewServer's
first-ping-order idle selection and unbounded tick counters, the
ack-before-view-change rule, primary state transfer with refusal to serve
until acked, one-outstanding-op forwarding, and the client's re-poll of
the view on every retry.

Workload model (same as the lab-1 twin): each of ``n_clients`` clients
Puts its own key W times, so the AMO/KV state per application collapses to
one last-executed-seq lane per client.

Node order: 0 = ViewServer, 1..NS = PBServers, NS+1.. = clients.

Lanes:
  ViewServer: [vn, prim, back, acked, next_rank] + per server [rank, ticks]
              (rank 0 = never pinged; rank order = dict insertion order,
              which breaks idle-selection ties, viewserver.py:112-116)
  PBServer s: [vn, prim, back, synced, pend_client+1, pend_seq] + amo[NC]
  Client c:   [k, vn, prim, back]          k = seq in flight, W+1 = done

Messages [tag, frm, to, payload...]:
  PING [vn]    GETVIEW []      VIEWREPLY [vn, prim, back]
  REQ [c, s]   REPLY [c, s]    FWD [vn, c, s]   FWDACK [vn, c, s]
  XFER [vn, prim, back, amo_0..amo_NC-1]        XFERACK [vn]
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from dslabs_tpu.tpu.engine import SENTINEL, TensorProtocol

__all__ = ["make_pb_protocol"]

PING, GETVIEW, VIEWREPLY, REQ, REPLY, FWD, FWDACK, XFER, XFERACK = range(9)
T_PINGCHECK, T_PING, T_CLIENT = 1, 2, 3
PINGCHECK_MS = 100
PING_MS = 25
CLIENT_MS = 100
DEAD_TICKS = 2


def make_pb_protocol(ns: int = 2, n_clients: int = 1, w: int = 1,
                     net_cap: int = 32, timer_cap: int = 4) -> TensorProtocol:
    NS, NC = ns, n_clients
    VSW = 5 + 2 * NS
    SW = 6 + NC
    CW = 4
    NW = VSW + NS * SW + NC * CW
    N_NODES = 1 + NS + NC
    PAYLOAD = max(3 + NC, 3)
    MW = 3 + PAYLOAD
    TW = 4
    # rows: vs 1 + server 2 + client 2 (see finalize calls below)
    MAX_SENDS = 5
    MAX_SETS = 3

    # ------------------------------------------------------- un/pack state

    def _unpack(nodes):
        st = {
            "vvn": nodes[0], "vp": nodes[1], "vb": nodes[2],
            "vack": nodes[3], "vnext": nodes[4],
            "rank": nodes[5:5 + 2 * NS:2], "ticks": nodes[6:5 + 2 * NS:2],
        }
        base = VSW
        st["svn"] = jnp.stack([nodes[base + s * SW + 0] for s in range(NS)])
        st["sp"] = jnp.stack([nodes[base + s * SW + 1] for s in range(NS)])
        st["sb"] = jnp.stack([nodes[base + s * SW + 2] for s in range(NS)])
        st["sync"] = jnp.stack([nodes[base + s * SW + 3] for s in range(NS)])
        st["pc"] = jnp.stack([nodes[base + s * SW + 4] for s in range(NS)])
        st["ps"] = jnp.stack([nodes[base + s * SW + 5] for s in range(NS)])
        st["amo"] = jnp.stack([nodes[base + s * SW + 6:base + s * SW + 6 + NC]
                               for s in range(NS)])
        cb = VSW + NS * SW
        st["k"] = jnp.stack([nodes[cb + c * CW + 0] for c in range(NC)])
        st["cvn"] = jnp.stack([nodes[cb + c * CW + 1] for c in range(NC)])
        st["cp"] = jnp.stack([nodes[cb + c * CW + 2] for c in range(NC)])
        st["cb"] = jnp.stack([nodes[cb + c * CW + 3] for c in range(NC)])
        return st

    def _repack(st):
        parts = [st["vvn"][None], st["vp"][None], st["vb"][None],
                 st["vack"][None], st["vnext"][None]]
        for s in range(NS):
            parts.extend([st["rank"][s][None], st["ticks"][s][None]])
        for s in range(NS):
            parts.extend([st["svn"][s][None], st["sp"][s][None],
                          st["sb"][s][None], st["sync"][s][None],
                          st["pc"][s][None], st["ps"][s][None],
                          st["amo"][s]])
        for c in range(NC):
            parts.extend([st["k"][c][None], st["cvn"][c][None],
                          st["cp"][c][None], st["cb"][c][None]])
        return jnp.concatenate(parts).astype(jnp.int32)

    # ------------------------------------------------------------ builders

    def mk_row(cond, tag, frm, to, payload):
        lanes = [jnp.asarray(tag, jnp.int32), jnp.asarray(frm, jnp.int32),
                 jnp.asarray(to, jnp.int32)]
        for v in payload:
            lanes.append(jnp.asarray(v, jnp.int32))
        while len(lanes) < MW:
            lanes.append(jnp.zeros((), jnp.int32))
        rec = jnp.stack(lanes)
        return jnp.where(cond, rec, jnp.full((MW,), SENTINEL, jnp.int32))

    def mk_set(cond, node, tag, ms, p0):
        rec = jnp.stack([jnp.asarray(node, jnp.int32),
                         jnp.asarray(tag, jnp.int32),
                         jnp.asarray(ms, jnp.int32),
                         jnp.asarray(ms, jnp.int32),
                         jnp.asarray(p0, jnp.int32)])
        return jnp.where(cond, rec, jnp.full((1 + TW,), SENTINEL, jnp.int32))

    class Rows:
        def __init__(self):
            self.rows = []

        def add(self, row):
            self.rows.append(row)

        def finalize(self, count):
            assert len(self.rows) <= count, (len(self.rows), count)
            blank = jnp.full((self.rows[0].shape[-1] if self.rows else MW,),
                             SENTINEL, jnp.int32)
            rows = list(self.rows)
            while len(rows) < count:
                rows.append(blank if rows else
                            jnp.full((MW,), SENTINEL, jnp.int32))
            return jnp.stack(rows)

    # -------------------------------------------------- ViewServer helpers

    def vs_alive(st, a):
        """a is a 1-based server id (0 = None)."""
        ai = (a - 1).clip(0, NS - 1)
        return (a > 0) & (st["rank"][ai] > 0) & (st["ticks"][ai] < DEAD_TICKS)

    def vs_idle(st):
        """First alive non-primary/backup server in first-ping (rank)
        order; 0 if none (viewserver.py:112-116)."""
        best_rank = jnp.full((), 1 << 30, jnp.int32)
        best = jnp.zeros((), jnp.int32)
        for s in range(NS):
            sid = s + 1
            ok = ((st["rank"][s] > 0) & (st["ticks"][s] < DEAD_TICKS)
                  & (st["vp"] != sid) & (st["vb"] != sid)
                  & (st["rank"][s] < best_rank))
            best_rank = jnp.where(ok, st["rank"][s], best_rank)
            best = jnp.where(ok, sid, best)
        return best

    def vs_evaluate(st, cond):
        """The view-change rules (viewserver.py:118-139), as masks."""
        prim, back, acked = st["vp"], st["vb"], st["vack"]
        idle = vs_idle(st)
        ap = vs_alive(st, prim)
        ab = vs_alive(st, back)
        c0 = cond & (prim == 0) & (idle > 0)                  # startup
        guard = cond & (prim != 0) & (acked == 1)
        c1 = guard & ~ap & ab                                 # promote backup
        c2 = guard & ~ap & (back == 0) & (idle > 0)           # dead solo prim
        c3 = guard & ap & (back != 0) & ~ab                   # replace backup
        c4 = guard & ap & (back == 0) & (idle > 0)            # fill backup
        did = c0 | c1 | c2 | c3 | c4
        np_ = jnp.where(c0, idle, jnp.where(c1, back, prim))
        nb = jnp.where(c0, 0, jnp.where(c1 | c2 | c3 | c4, idle, back))
        # c1's idle excludes the OLD primary/backup — correct: the old
        # primary is dead and the old backup is the new primary, and
        # vs_idle already skipped both.
        st["vp"] = jnp.where(did, np_, prim).astype(jnp.int32)
        st["vb"] = jnp.where(did, nb, back).astype(jnp.int32)
        st["vvn"] = jnp.where(did, st["vvn"] + 1, st["vvn"]).astype(jnp.int32)
        st["vack"] = jnp.where(did, 0, st["vack"]).astype(jnp.int32)

    def vs_view_reply(st, cond, to, sends: Rows):
        sends.add(mk_row(cond, VIEWREPLY, 0, to,
                         [st["vvn"], st["vp"], st["vb"]]))

    # ---------------------------------------------------- PBServer helpers

    def srv_adopt(st, s, view, sends: Rows, can_send: bool):
        """_adopt (pb.py:123-137) for server index s (0-based). view =
        (vn, prim, back) lanes; cond rides inside view[0] > svn."""
        sid = s + 1
        vn, prim, back = view
        do = vn > st["svn"][s]
        st["svn"] = st["svn"].at[s].set(
            jnp.where(do, vn, st["svn"][s]).astype(jnp.int32))
        st["sp"] = st["sp"].at[s].set(
            jnp.where(do, prim, st["sp"][s]).astype(jnp.int32))
        st["sb"] = st["sb"].at[s].set(
            jnp.where(do, back, st["sb"][s]).astype(jnp.int32))
        st["pc"] = st["pc"].at[s].set(
            jnp.where(do, 0, st["pc"][s]).astype(jnp.int32))
        st["ps"] = st["ps"].at[s].set(
            jnp.where(do, 0, st["ps"][s]).astype(jnp.int32))
        is_p = do & (prim == sid)
        is_b = do & (back == sid)
        new_sync = jnp.where(
            is_p, jnp.where(back != 0, 0, 1),
            jnp.where(is_b, 0, 1))
        st["sync"] = st["sync"].at[s].set(
            jnp.where(do, new_sync, st["sync"][s]).astype(jnp.int32))
        if can_send:
            xfer = is_p & (back != 0)
            sends.add(mk_row(xfer, XFER, sid, back,
                             [vn, prim, back] + [st["amo"][s][c]
                                                 for c in range(NC)]))
        return do

    # ----------------------------------------------------- message handler

    def step_message(nodes, msg):
        tag, frm, to = msg[0], msg[1], msg[2]
        p = msg[3:]
        st = _unpack(nodes)

        # ---------------- ViewServer (node 0)
        vs_here = to == 0
        vs_sends = Rows()
        is_ping = vs_here & (tag == PING)
        si = (frm - 1).clip(0, NS - 1)
        # first ping assigns the next rank (dict insertion order)
        newcomer = is_ping & (st["rank"][si] == 0)
        st["vnext"] = jnp.where(newcomer, st["vnext"] + 1,
                                st["vnext"]).astype(jnp.int32)
        st["rank"] = st["rank"].at[si].set(
            jnp.where(newcomer, st["vnext"], st["rank"][si]).astype(jnp.int32))
        st["ticks"] = st["ticks"].at[si].set(
            jnp.where(is_ping, 0, st["ticks"][si]).astype(jnp.int32))
        st["vack"] = jnp.where(
            is_ping & (frm == st["vp"]) & (p[0] == st["vvn"]),
            1, st["vack"]).astype(jnp.int32)
        vs_evaluate(st, is_ping)
        is_gv = vs_here & (tag == GETVIEW)
        vs_view_reply(st, is_ping | is_gv, frm, vs_sends)
        vs_rows = vs_sends.finalize(1)

        # ---------------- PBServers (nodes 1..NS)
        srv_rows = None
        for s in range(NS):
            sid = s + 1
            here = to == sid
            sends = Rows()
            # handle_ViewReply -> _adopt (may send a state transfer)
            is_vr = here & (tag == VIEWREPLY)
            srv_adopt(st, s, (jnp.where(is_vr, p[0], -1), p[1], p[2]),
                      sends, can_send=True)

            # handle_Request (pb.py:155-171)
            is_rq = here & (tag == REQ)
            c, sq = p[0].clip(0, NC - 1), p[1]
            serving = (is_rq & (st["sp"][s] == sid)
                       & (st["sync"][s] == 1))
            amo_c = st["amo"][s][c]
            already = serving & (sq <= amo_c)
            reply_cached = already & (sq == amo_c)
            solo = serving & ~already & (st["sb"][s] == 0)
            st["amo"] = st["amo"].at[s, c].set(
                jnp.where(solo, sq, st["amo"][s][c]).astype(jnp.int32))
            can_fwd = (serving & ~already & (st["sb"][s] != 0)
                       & (st["pc"][s] == 0))
            st["pc"] = st["pc"].at[s].set(
                jnp.where(can_fwd, c + 1, st["pc"][s]).astype(jnp.int32))
            st["ps"] = st["ps"].at[s].set(
                jnp.where(can_fwd, sq, st["ps"][s]).astype(jnp.int32))

            # handle_ForwardRequest (backup executes + acks)
            is_fw = here & (tag == FWD)
            fw_ok = (is_fw & (st["sb"][s] == sid)
                     & (p[0] == st["svn"][s]) & (st["sync"][s] == 1))
            fc, fs = p[1].clip(0, NC - 1), p[2]
            st["amo"] = st["amo"].at[s, fc].set(
                jnp.where(fw_ok & (fs > st["amo"][s][fc]), fs,
                          st["amo"][s][fc]).astype(jnp.int32))

            # handle_ForwardAck (primary commits + replies)
            is_fa = here & (tag == FWDACK)
            fa_ok = (is_fa & (st["sp"][s] == sid)
                     & (p[0] == st["svn"][s])
                     & (st["pc"][s] == p[1] + 1) & (st["ps"][s] == p[2]))
            ac, asq = p[1].clip(0, NC - 1), p[2]
            st["pc"] = st["pc"].at[s].set(
                jnp.where(fa_ok, 0, st["pc"][s]).astype(jnp.int32))
            st["ps"] = st["ps"].at[s].set(
                jnp.where(fa_ok, 0, st["ps"][s]).astype(jnp.int32))
            fa_reply = fa_ok & (asq >= st["amo"][s][ac])
            st["amo"] = st["amo"].at[s, ac].set(
                jnp.where(fa_ok & (asq > st["amo"][s][ac]), asq,
                          st["amo"][s][ac]).astype(jnp.int32))

            # handle_StateTransfer (pb.py:190-199)
            is_xf = here & (tag == XFER)
            mine = is_xf & (p[2] == sid)
            srv_adopt(st, s, (jnp.where(mine, p[0], -1), p[1], p[2]),
                      sends, can_send=False)
            cur = mine & (st["svn"][s] == p[0])
            install = cur & (st["sync"][s] == 0)
            for c2 in range(NC):
                st["amo"] = st["amo"].at[s, c2].set(
                    jnp.where(install, p[3 + c2],
                              st["amo"][s][c2]).astype(jnp.int32))
            st["sync"] = st["sync"].at[s].set(
                jnp.where(install, 1, st["sync"][s]).astype(jnp.int32))

            # handle_StateTransferAck
            is_xa = here & (tag == XFERACK)
            xa_ok = is_xa & (st["sp"][s] == sid) & (st["svn"][s] == p[0])
            st["sync"] = st["sync"].at[s].set(
                jnp.where(xa_ok, 1, st["sync"][s]).astype(jnp.int32))

            # merged reply row (mutually exclusive reply branches)
            rep = reply_cached | solo | fa_reply
            rep_c = jnp.where(fa_reply, ac, c)
            rep_s = jnp.where(fa_reply, asq, sq)
            sends.add(jnp.minimum(jnp.minimum(
                mk_row(rep, REPLY, sid, 1 + NS + rep_c, [rep_c, rep_s]),
                mk_row(can_fwd, FWD, sid, st["sb"][s],
                       [st["svn"][s], c, sq])),
                jnp.minimum(
                    mk_row(fw_ok, FWDACK, sid, frm, [p[0], fc, fs]),
                    mk_row(cur, XFERACK, sid, frm, [p[0]]))))
            r = sends.finalize(2)
            srv_rows = r if srv_rows is None else jnp.minimum(srv_rows, r)

        # ---------------- Clients (nodes NS+1..)
        cli_rows, cli_sets = None, None
        for c in range(NC):
            cid = 1 + NS + c
            here = to == cid
            sends, sets = Rows(), Rows()
            # handle_ViewReply (pb.py:243-247); cvn == -1 means view=None
            # (distinct from an adopted View(0, None, None) in the object)
            is_vr = here & (tag == VIEWREPLY)
            newer = is_vr & ((st["cvn"][c] == -1) | (p[0] > st["cvn"][c]))
            st["cvn"] = st["cvn"].at[c].set(
                jnp.where(newer, p[0], st["cvn"][c]).astype(jnp.int32))
            st["cp"] = st["cp"].at[c].set(
                jnp.where(newer, p[1], st["cp"][c]).astype(jnp.int32))
            st["cb"] = st["cb"].at[c].set(
                jnp.where(newer, p[2], st["cb"][c]).astype(jnp.int32))
            k = st["k"][c]
            waiting = k <= w
            vr_send = newer & waiting & (st["cp"][c] > 0)
            vr_gv = newer & waiting & (st["cp"][c] == 0)

            # handle_Reply — worker pumps the next command
            is_rp = here & (tag == REPLY) & (p[0] == c)
            match = is_rp & (p[1] == k) & waiting
            k2 = jnp.where(match, k + 1, k)
            st["k"] = st["k"].at[c].set(k2.astype(jnp.int32))
            has_next = match & (k2 <= w)
            nx_req = has_next & (st["cp"][c] > 0)
            nx_gv = has_next & (st["cp"][c] == 0)
            seq = jnp.where(has_next, k2, k)
            sends.add(jnp.minimum(
                mk_row(vr_send, REQ, cid, st["cp"][c], [c, k]),
                mk_row(nx_req, REQ, cid, st["cp"][c], [c, seq])))
            sends.add(mk_row(vr_gv | nx_gv, GETVIEW, cid, 0, []))
            sets.add(mk_set(has_next, cid, T_CLIENT, CLIENT_MS, k2))
            r = sends.finalize(2)
            t = sets.finalize(1)
            cli_rows = r if cli_rows is None else jnp.minimum(cli_rows, r)
            cli_sets = t if cli_sets is None else jnp.minimum(cli_sets, t)

        rows = jnp.concatenate([vs_rows, srv_rows, cli_rows])
        blank_sets = jnp.full((MAX_SETS - 1, 1 + TW), SENTINEL, jnp.int32)
        tsets = jnp.concatenate([cli_sets, blank_sets])
        return _repack(st), rows, tsets

    # ------------------------------------------------------ timer handler

    def step_timer(nodes, node_idx, timer):
        tag, p0 = timer[0], timer[3]
        st = _unpack(nodes)

        # ---- ViewServer PingCheckTimer (viewserver.py:101-105)
        is_pc = (node_idx == 0) & (tag == T_PINGCHECK)
        for s in range(NS):
            known = is_pc & (st["rank"][s] > 0)
            st["ticks"] = st["ticks"].at[s].set(
                jnp.where(known, st["ticks"][s] + 1,
                          st["ticks"][s]).astype(jnp.int32))
        vs_evaluate(st, is_pc)
        vs_sets = mk_set(is_pc, 0, T_PINGCHECK, PINGCHECK_MS, 0)

        # ---- PBServer PingTimer (pb.py:144-153)
        srv_rows, srv_sets = None, None
        for s in range(NS):
            sid = s + 1
            here = (node_idx == sid) & (tag == T_PING)
            sends = Rows()
            is_p = st["sp"][s] == sid
            has_b = st["sb"][s] != 0
            # svn == -1 means view=None (pings 0, pb.py:114-121)
            acked_vn = jnp.where(
                st["svn"][s] == -1, 0,
                jnp.where(is_p & has_b & (st["sync"][s] == 0),
                          st["svn"][s] - 1, st["svn"][s]))
            sends.add(mk_row(here, PING, sid, 0, [acked_vn]))
            resend_x = here & is_p & has_b & (st["sync"][s] == 0)
            refwd = (here & is_p & has_b & (st["sync"][s] == 1)
                     & (st["pc"][s] > 0))
            sends.add(jnp.minimum(
                mk_row(resend_x, XFER, sid, st["sb"][s],
                       [st["svn"][s], st["sp"][s], st["sb"][s]]
                       + [st["amo"][s][c] for c in range(NC)]),
                mk_row(refwd, FWD, sid, st["sb"][s],
                       [st["svn"][s], st["pc"][s] - 1, st["ps"][s]])))
            t = mk_set(here, sid, T_PING, PING_MS, 0)
            r = sends.finalize(2)
            srv_rows = r if srv_rows is None else jnp.minimum(srv_rows, r)
            srv_sets = t if srv_sets is None else jnp.minimum(srv_sets, t)

        # ---- Client ClientTimer (pb.py:256-260)
        cli_rows, cli_sets = None, None
        for c in range(NC):
            cid = 1 + NS + c
            here = (node_idx == cid) & (tag == T_CLIENT)
            k = st["k"][c]
            live = here & (p0 == k) & (k <= w)
            sends = Rows()
            sends.add(mk_row(live, GETVIEW, cid, 0, []))
            sends.add(mk_row(live & (st["cp"][c] > 0), REQ, cid,
                             st["cp"][c], [c, k]))
            t = mk_set(live, cid, T_CLIENT, CLIENT_MS, k)
            r = sends.finalize(2)
            cli_rows = r if cli_rows is None else jnp.minimum(cli_rows, r)
            cli_sets = t if cli_sets is None else jnp.minimum(cli_sets, t)

        rows = jnp.concatenate([
            jnp.full((1, MW), SENTINEL, jnp.int32), srv_rows, cli_rows])
        tsets = jnp.stack([vs_sets, srv_sets, cli_sets])
        return _repack(st), rows, tsets

    # ------------------------------------------------------------ initials

    def init_nodes():
        return np.array(
            [0] * VSW
            + sum([[-1, 0, 0, 1, 0, 0] + [0] * NC for _ in range(NS)], [])
            + sum([[1, -1, 0, 0] for _ in range(NC)], []), np.int32)

    def init_messages():
        msgs = []
        for s in range(NS):
            rec = np.zeros((MW,), np.int32)
            rec[0:3] = [PING, s + 1, 0]
            msgs.append(rec)
        for c in range(NC):
            rec = np.zeros((MW,), np.int32)
            rec[0:3] = [GETVIEW, 1 + NS + c, 0]
            msgs.append(rec)
        return np.stack(msgs)

    def init_timers():
        recs = [[0, T_PINGCHECK, PINGCHECK_MS, PINGCHECK_MS, 0]]
        for s in range(NS):
            recs.append([s + 1, T_PING, PING_MS, PING_MS, 0])
        for c in range(NC):
            recs.append([1 + NS + c, T_CLIENT, CLIENT_MS, CLIENT_MS, 1])
        return np.array(recs, np.int32)

    def msg_dest(msg):
        return msg[2]

    def clients_done(state):
        done = jnp.asarray(True)
        cb = VSW + NS * SW
        for c in range(NC):
            done = done & (state["nodes"][cb + c * CW] == w + 1)
        return done

    return TensorProtocol(
        name=f"pb-s{NS}-c{NC}-w{w}",
        n_nodes=N_NODES,
        node_width=NW,
        msg_width=MW,
        timer_width=TW,
        net_cap=net_cap,
        timer_cap=timer_cap,
        max_sends=MAX_SENDS,
        max_sets=MAX_SETS,
        init_nodes=init_nodes,
        init_messages=init_messages,
        init_timers=init_timers,
        step_message=step_message,
        step_timer=step_timer,
        msg_dest=msg_dest,
        goals={"CLIENTS_DONE": clients_done},
    )
