"""Symmetry reduction: canonical ordering of indistinguishable node ids
(ISSUE 15 leg (b), ROADMAP #4b — the classic explicit-state trick).

A spec that declares ``symmetry=("acceptor", ...)`` groups marks those
node kinds' instances as interchangeable: any permutation of the group
is an automorphism of the transition system (the C5 conformance rule
statically rejects handlers that branch on WHICH member they are).
Every permutation image of a reachable state is therefore behaviorally
identical — exploring one representative per orbit covers them all,
cutting the reachable set by up to ``|group|!``.

``ProtocolSpec.compile()`` turns the declaration into a
:class:`SymmetrySpec` — static permutation tables over the packed node
lanes (instance blocks swap, group-indexed array fields permute their
elements) and the node-id relabel map.  :func:`build_canonicalizer`
compiles those tables into a fused device pass the engines run RIGHT
BEFORE fingerprinting (opt-in, default OFF — canonical unique counts
differ from raw counts by design, so the pinned lab counts stay
untouched unless a caller asks):

  for each permutation p:  candidate_p = apply(p, rows)
      - node lanes gather through the static lane_src table,
      - message records relabel from/to through the relab map and the
        network re-sorts to canonical order (sorted-set hashing),
      - per-node timer queues permute with their nodes,
      - the exception lane rides along unchanged;
  canonical(rows) = lexicographic min over candidates.

Only the FINGERPRINT sees the canonical form — stored frontier rows
stay the original states, so witnesses, traces, and predicate flags
replay on real reachable states; symmetric twins simply hash equal and
dedup to whichever representative arrived first.  Wired into both
engines' hash step and the sharded owner-hash via the shared
``_expand_chunk`` fingerprint site (owner routing keys on the canonical
fingerprint, so twins land on one owner and dedup exactly).

Scope (first cut, documented): the from/to lanes of the compiler's
uniform message records are relabeled; message/timer PAYLOAD fields and
timer records carrying raw node ids are NOT — specs should identify
senders via ``_from`` and index per-member state with ``index_group``
fields (my kingdom for a dependent type system).  The conformance
linter's C5 rule flags the detectable violations.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import numpy as np

__all__ = ["SymmetrySpec", "build_canonicalizer"]


@dataclasses.dataclass(frozen=True)
class SymmetrySpec:
    """Static permutation tables for one protocol's symmetry groups.

    ``relab``    [P, n_nodes]    relab[p][old_node_id] = new_node_id
    ``lane_src`` [P, node_width] new_nodes[l] = old_nodes[lane_src[p][l]]
    ``groups``   ((kind, base, count), ...) for reporting
    ``msg_node_lanes``  message-record lanes holding node ids (the
                        compiler's uniform [tag, frm, to, ...] layout)
    """

    relab: np.ndarray
    lane_src: np.ndarray
    groups: Tuple[Tuple[str, int, int], ...] = ()
    msg_node_lanes: Tuple[int, ...] = (1, 2)

    @property
    def n_perms(self) -> int:
        return int(self.relab.shape[0])


def build_canonicalizer(protocol, offsets) -> Callable:
    """Compile ``protocol.symmetry`` into the fused canonicalize pass:
    ``fn(rows [N, lanes] int32) -> [N, lanes] int32`` (pure jnp —
    traces into the engines' expand programs).  ``offsets`` is the
    engine's ``(o_net, o_timers, o_exc)`` flat-row split."""
    import jax
    import jax.numpy as jnp

    from dslabs_tpu.tpu.engine import (SENTINEL, _row_less,
                                       canonicalize_net)

    sym: SymmetrySpec = protocol.symmetry
    if sym is None:
        raise ValueError(f"{protocol.name}: no symmetry groups declared")
    p = protocol
    o0, o1, o2 = offsets
    nn = p.n_nodes
    relab = np.asarray(sym.relab, np.int64)
    lane_src = np.asarray(sym.lane_src, np.int64)
    n_perms = relab.shape[0]
    # Timer-axis gather: new_timers[j] = old_timers[inv[j]] where
    # relab[old] = new  =>  inv[new] = old.
    inv = np.zeros_like(relab)
    for k in range(n_perms):
        inv[k][relab[k]] = np.arange(nn)

    def _apply(rows, k):
        n = rows.shape[0]
        nodes = rows[:, :o0]
        if not (lane_src[k] == np.arange(o0)).all():
            nodes = jnp.take(nodes, lane_src[k], axis=1)
        net = rows[:, o0:o1].reshape(n, p.net_cap, p.msg_width)
        occ = net[:, :, 0] != SENTINEL
        rel = relab[k]
        if not (rel == np.arange(nn)).all():
            cols = []
            for lane in range(p.msg_width):
                col = net[:, :, lane]
                if lane in sym.msg_node_lanes:
                    # One-hot relabel (nn is small; dynamic gathers
                    # are the measured slow path under the flat vmap).
                    new = jnp.zeros_like(col)
                    for j in range(nn):
                        new = new + jnp.where(col == j,
                                              jnp.int32(int(rel[j])), 0)
                    col = jnp.where(occ, new, col)
                cols.append(col)
            net = jnp.stack(cols, axis=2)
            # Relabeled records break the canonical sorted-set order;
            # re-canonicalize so equal sets hash equal.
            net = jax.vmap(canonicalize_net)(net)
        timers = rows[:, o1:o2].reshape(n, nn, p.timer_cap,
                                        p.timer_width)
        if not (inv[k] == np.arange(nn)).all():
            timers = jnp.take(timers, inv[k], axis=1)
        return jnp.concatenate([
            nodes, net.reshape(n, -1), timers.reshape(n, -1),
            rows[:, o2:o2 + 1]], axis=1)

    def canonicalize(rows):
        # Permutation 0 is the identity (pinned by the compiler):
        # candidate 0 is the input itself.
        best = rows
        for k in range(1, n_perms):
            cand = _apply(rows, k)
            best = jnp.where(_row_less(cand, best)[:, None], cand, best)
        return best

    return canonicalize
